"""Span recorder + fleet-wide delta tracing: Chrome-trace JSON rings and
the end-to-end stage attribution that rides the serving plane.

Load any export in Perfetto (https://ui.perfetto.dev) or chrome://tracing.
Spans nest step -> operator eval -> exchange on the host path (driven by
:class:`~dbsp_tpu.obs.instrument.CircuitInstrumentation` from the
scheduler-event stream) and tick -> compiled-step/validate/maintain on the
compiled path (driven by the compiled driver directly).

Format: the JSON-object flavor of the Trace Event Format — ``B``/``E``
duration events with microsecond timestamps, so nesting is explicit and a
consumer (or test) can check balance. Events carry the real ``os.getpid()``
and ``threading.get_native_id()`` so the serving plane's thread fan-out
(HTTP handlers, circuit loop, replica feed loops) lands in distinct lanes,
with ``M`` metadata events naming each process and thread. The window is
bounded: only the most recent ``max_steps`` completed top-level spans are
retained (a serving pipeline runs forever; the trace buffer must not);
evictions are counted in ``dropped_steps`` and exported as
``dbsp_tpu_obs_trace_dropped_total{pipeline}`` once :meth:`SpanRecorder.bind`
has run.

The second half of this module is the fleet-wide delta path. Every ingested
batch gets a trace context (id + stage timestamps) that flows

    push -> Controller._step_locked tick -> ReadPlane.publish
         -> changefeed record -> ReplicaServer._apply -> read response

so an end-to-end "delta age" decomposes exactly into the closed stage set
:data:`E2E_STAGES`:

``queue_wait``
    ingest wall-time to the start of the tick that drained the batch.
``tick``
    the draining tick's wall-clock (step + output emission).
``publish``
    tick end to the validation publish that made the delta readable —
    includes the deferred-validation dwell on the compiled path.
``transport``
    publish to changefeed receipt at a replica (HTTP long-poll hop).
``apply``
    the replica's fold of the changefeed records into its view state.
``serve``
    the read handler's own latency (snapshot/index lookup + encode).

The writer-side stages use one wall-clock (``time.time``) timeline, so
``queue_wait + tick + publish == publish_ts - ingest_ts`` exactly; replica
stages extend the same timeline across the (same-host) process boundary.
Stage latencies land in ``dbsp_tpu_e2e_stage_seconds{stage}``, in span
rings (as ``e2e`` category spans carrying the trace ids), in the timeline
(``e2e_stage`` records EXPLAIN SPIKE attributes outliers to), and on every
``/view`` response as ``age_s`` + ``stages``. Kill switch:
``DBSP_TPU_TRACE_E2E=0`` (default on, like the read plane's).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional, Sequence

from dbsp_tpu.testing.tsan import maybe_instrument as _tsan_hook

__all__ = [
    "SpanRecorder", "E2ETracer", "E2E_STAGES", "trace_e2e_enabled",
    "merge_chrome_traces",
]

#: closed stage taxonomy of the end-to-end delta path, in path order.
#: ``dbsp_tpu_e2e_stage_seconds{stage}`` only ever carries these values.
E2E_STAGES = ("queue_wait", "tick", "publish", "transport", "apply", "serve")

#: trace ids carried per published epoch are capped (a firehose tick can
#: drain thousands of batches; the annotation rides every feed record)
_MAX_IDS_PER_EPOCH = 16


def trace_e2e_enabled(env: Optional[dict] = None) -> bool:
    """Kill switch for end-to-end delta tracing: ``DBSP_TPU_TRACE_E2E=0``
    disables it (default on, mirroring ``readplane_enabled``)."""
    env = os.environ if env is None else env
    return str(env.get("DBSP_TPU_TRACE_E2E", "1")).lower() not in (
        "0", "false", "no", "off")


class SpanRecorder:
    """Accumulates B/E span events; ring-buffered per top-level span.

    Events are stamped with the recorder's process id and the *real* native
    thread id of the caller, with per-thread open-span stacks so concurrent
    serving-plane threads (circuit loop, HTTP handlers, replica feed loop)
    nest correctly in their own lanes instead of interleaving into one.
    """

    def __init__(self, max_steps: int = 64, process: str = "dbsp_tpu"):
        self.pid = os.getpid()
        self.process = process
        self._steps: Deque[List[dict]] = deque(maxlen=max_steps)
        self._open: Dict[int, List[dict]] = {}   # tid -> in-flight events
        self._depth: Dict[int, int] = {}         # tid -> open-span depth
        self._threads: Dict[int, str] = {}       # tid -> thread name
        self._lock = threading.Lock()
        self.dropped_steps = 0
        self._dropped_counter = None  # wired once by bind()
        self._pipeline = ""
        _tsan_hook(self)

    # -- recording ----------------------------------------------------------
    def _push_step_locked(self, events: List[dict]) -> None:  # holds: _lock
        if len(self._steps) == self._steps.maxlen:
            self.dropped_steps += 1
        self._steps.append(events)

    def begin(self, name: str, cat: str = "operator",
              ts_ns: Optional[int] = None, args: Optional[dict] = None) -> None:
        ts = (ts_ns if ts_ns else time.perf_counter_ns()) / 1e3
        tid = threading.get_native_id()
        ev = {"name": name, "cat": cat, "ph": "B",
              "ts": ts, "pid": self.pid, "tid": tid}
        if args:
            ev["args"] = args
        with self._lock:
            if tid not in self._threads:
                self._threads[tid] = threading.current_thread().name
            self._open.setdefault(tid, []).append(ev)
            self._depth[tid] = self._depth.get(tid, 0) + 1

    def end(self, name: str, ts_ns: Optional[int] = None) -> None:
        ts = (ts_ns if ts_ns else time.perf_counter_ns()) / 1e3
        tid = threading.get_native_id()
        with self._lock:
            depth = self._depth.get(tid, 0)
            if depth == 0:
                return  # unbalanced end (attached mid-step): drop
            self._open[tid].append({"name": name, "ph": "E", "ts": ts,
                                    "pid": self.pid, "tid": tid})
            depth -= 1
            self._depth[tid] = depth
            if depth == 0:
                self._push_step_locked(self._open.pop(tid))

    def instant(self, name: str, cat: str = "event",
                ts_ns: Optional[int] = None,
                args: Optional[dict] = None) -> None:
        """A zero-duration marker (overflow replays, re-traces, ...)."""
        ts = (ts_ns if ts_ns else time.perf_counter_ns()) / 1e3
        tid = threading.get_native_id()
        ev = {"name": name, "cat": cat, "ph": "i", "ts": ts,
              "pid": self.pid, "tid": tid, "s": "t"}
        if args:
            ev["args"] = args
        with self._lock:
            if tid not in self._threads:
                self._threads[tid] = threading.current_thread().name
            if self._depth.get(tid, 0):
                self._open[tid].append(ev)
            else:
                self._push_step_locked([ev])

    def span_at(self, name: str, t0_ns: int, t1_ns: int,
                cat: str = "e2e", args: Optional[dict] = None) -> None:
        """Append one already-completed span as a self-contained, balanced
        ``[B, E]`` ring entry — the e2e stage spans use this, so a trace
        snapshot taken mid-tick can never observe them half-open."""
        tid = threading.get_native_id()
        bev = {"name": name, "cat": cat, "ph": "B", "ts": t0_ns / 1e3,
               "pid": self.pid, "tid": tid}
        if args:
            bev["args"] = args
        eev = {"name": name, "ph": "E", "ts": max(t0_ns, t1_ns) / 1e3,
               "pid": self.pid, "tid": tid}
        with self._lock:
            if tid not in self._threads:
                self._threads[tid] = threading.current_thread().name
            self._push_step_locked([bev, eev])

    class _Span:
        __slots__ = ("rec", "name", "cat")

        def __init__(self, rec, name, cat):
            self.rec, self.name, self.cat = rec, name, cat

        def __enter__(self):
            self.rec.begin(self.name, self.cat)
            return self

        def __exit__(self, *exc):
            self.rec.end(self.name)
            return False

    def span(self, name: str, cat: str = "operator") -> "_Span":
        """Context-manager convenience for host-driven span pairs."""
        return SpanRecorder._Span(self, name, cat)

    # -- export -------------------------------------------------------------
    def bind(self, registry=None, pipeline: str = "") -> None:
        """Export drop accounting: mirrors ``dropped_steps`` into
        ``dbsp_tpu_obs_trace_dropped_total{pipeline}`` at scrape time (the
        flight recorder got exactly this in its PR; the span ring never
        did). Idempotent; called once at obs attach, before traffic."""
        if registry is None or self._dropped_counter is not None:
            return
        counter = registry.counter(
            "dbsp_tpu_obs_trace_dropped_total",
            "Completed top-level spans evicted from the bounded span ring "
            "(/trace is truncated history once this grows)",
            labels=("pipeline",))
        self._pipeline = pipeline
        self._dropped_counter = counter
        registry.register_collector(self._export)

    def _export(self) -> None:
        self._dropped_counter.labels(pipeline=self._pipeline).set_total(
            float(self.dropped_steps))

    def events(self) -> List[dict]:
        with self._lock:
            return [ev for step in self._steps for ev in step]

    def to_chrome_trace(self) -> dict:
        with self._lock:
            evs = [ev for step in self._steps for ev in step]
            threads = dict(self._threads)
            dropped = self.dropped_steps
        meta = [{"name": "process_name", "ph": "M", "pid": self.pid,
                 "tid": 0, "args": {"name": self.process}}]
        for tid in sorted(threads):
            meta.append({"name": "thread_name", "ph": "M", "pid": self.pid,
                         "tid": tid, "args": {"name": threads[tid]}})
        return {"traceEvents": meta + evs, "displayTimeUnit": "ms",
                "otherData": {"dropped_steps": dropped,
                              "truncated": dropped > 0,
                              "process": self.process, "pid": self.pid}}

    def to_json(self) -> str:
        return json.dumps(self.to_chrome_trace())

    def clear(self) -> None:
        with self._lock:
            self._steps.clear()
            self._open = {}
            self._depth = {}


def merge_chrome_traces(traces: Sequence[dict]) -> dict:
    """Merge per-process Chrome-trace exports into one Perfetto-loadable
    fleet trace: concatenates ``traceEvents`` (each ring already carries
    its own real pid lanes), dedups identical ``M`` metadata events, and
    folds the per-ring drop accounting into ``otherData``."""
    events: List[dict] = []
    seen_meta = set()
    processes: List[dict] = []
    dropped = 0
    for doc in traces:
        if not doc:
            continue
        for ev in doc.get("traceEvents", ()):
            if ev.get("ph") == "M":
                key = (ev.get("name"), ev.get("pid"), ev.get("tid"),
                       str(ev.get("args")))
                if key in seen_meta:
                    continue
                seen_meta.add(key)
            events.append(ev)
        other = doc.get("otherData", {})
        dropped += int(other.get("dropped_steps", 0) or 0)
        if "process" in other:
            processes.append({"process": other.get("process"),
                              "pid": other.get("pid"),
                              "dropped_steps": other.get("dropped_steps", 0)})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"dropped_steps": dropped,
                          "truncated": dropped > 0,
                          "processes": processes}}


class E2ETracer:
    """Per-process end-to-end delta tracker: batch trace contexts move
    through three pools as the delta path advances —

    ``_pending``  (note_ingest)   arrived, awaiting a tick
    ``_in_tick``  (tick_begin)    drained into the in-flight tick
    ``_awaiting`` (tick_end)      ticked, awaiting validation publish

    — and are sealed per epoch by :meth:`note_publish` into ``_by_epoch``,
    the bounded annotation map read routes and changefeed records resolve
    stage breakdowns from. The annotation dict is JSON-safe and rides
    ``rec["trace"]`` on every changefeed record, which is how the context
    crosses to replicas (same-host wall clock makes the transport stage a
    plain subtraction).

    Everything mutable sits behind one leaf lock (``_lock``); the metric/
    span/timeline side effects happen outside it via the two-phase
    ``note_publish`` / ``flush_publish`` split so the read plane never
    holds its own lock across an observation.
    """

    def __init__(self, enabled: Optional[bool] = None,
                 max_pending: int = 4096, max_epochs: int = 256):
        self.enabled = trace_e2e_enabled() if enabled is None else bool(enabled)
        self.max_pending = max_pending
        self.max_epochs = max_epochs
        self._lock = threading.Lock()
        self._seq = 0
        self._pending: List[dict] = []
        self._in_tick: List[dict] = []
        self._awaiting: List[dict] = []
        self._tick_t0: Optional[float] = None
        self._by_epoch: "OrderedDict[int, dict]" = OrderedDict()
        self.dropped = 0
        self._hist = None      # wired once by bind()
        self._spans = None
        self._timeline = None
        _tsan_hook(self)

    # -- wiring -------------------------------------------------------------
    def bind(self, registry=None, spans=None, timeline=None) -> None:
        """Wire export surfaces (idempotent for the registry; called once
        at obs attach, before traffic)."""
        if spans is not None:
            self._spans = spans
        if timeline is not None:
            self._timeline = timeline
        if registry is not None and self._hist is None:
            from dbsp_tpu.obs.registry import default_latency_buckets
            self._hist = registry.histogram(
                "dbsp_tpu_e2e_stage_seconds",
                "Per-stage latency of the end-to-end delta path "
                "ingest->tick->publish->changefeed->replica->read (closed "
                "stage set: obs.tracing.E2E_STAGES; writer stages sampled "
                "once per published epoch, replica stages once per applied "
                "changefeed batch, serve once per read)",
                labels=("stage",), buckets=default_latency_buckets())

    # -- writer-side path ---------------------------------------------------
    def note_ingest(self, rows: int, ts: Optional[float] = None,
                    trace_id: Optional[str] = None) -> Optional[str]:
        """Stamp one arrived batch; returns its trace id (caller-supplied
        via the ``X-Dbsp-Trace`` header, or freshly minted)."""
        if not self.enabled or rows <= 0:
            return None
        now = time.time() if ts is None else ts
        with self._lock:
            if len(self._pending) >= self.max_pending:
                self.dropped += 1
                return None
            if trace_id is None:
                self._seq += 1
                trace_id = "%x-%d" % (os.getpid(), self._seq)
            self._pending.append(
                {"id": trace_id, "ingest_ts": now, "rows": rows})
        return trace_id

    def tick_begin(self) -> None:
        """The tick that is about to drain the input queues starts: every
        pending context's queue_wait ends here. Called by the controller
        *before* it drains ``_pushed``/endpoint rows, so any context
        stamped earlier has its rows included in this tick."""
        if not self.enabled:
            return
        now = time.time()
        with self._lock:
            batch, self._pending = self._pending, []
            self._tick_t0 = now
            for ctx in batch:
                ctx["queue_wait_s"] = max(0.0, now - ctx["ingest_ts"])
            self._in_tick.extend(batch)

    def tick_end(self) -> List[str]:
        """The tick finished (step + output emission): contexts move to
        the awaiting-publish pool. Returns the batch trace ids so the
        controller can link its timeline tick record to them."""
        if not self.enabled:
            return []
        now = time.time()
        with self._lock:
            t0, self._tick_t0 = self._tick_t0, None
            moved, self._in_tick = self._in_tick, []
            tick_s = max(0.0, now - t0) if t0 is not None else 0.0
            for ctx in moved:
                ctx["tick_s"] = tick_s
                ctx["tick_end_ts"] = now
            self._awaiting.extend(moved)
            return [ctx["id"] for ctx in moved[:_MAX_IDS_PER_EPOCH]]

    def note_publish(self, epoch: int,
                     ts: Optional[float] = None) -> Optional[dict]:
        """Seal every awaiting context into epoch ``epoch``'s annotation
        (called by ``ReadPlane.publish`` under the plane lock — state move
        only; pass the result to :meth:`flush_publish` after the plane
        lock is released for the metric/span/timeline effects).

        Stage arithmetic is exact for the oldest batch: queue_wait + tick
        + publish sum to ``publish_ts - ingest_ts`` on one wall clock.
        """
        if not self.enabled:
            return None
        now = time.time() if ts is None else ts
        with self._lock:
            moved, self._awaiting = self._awaiting, []
            if not moved:
                return None
            oldest = min(moved, key=lambda c: c["ingest_ts"])
            ann = {
                "ids": [c["id"] for c in moved[:_MAX_IDS_PER_EPOCH]],
                "n": len(moved),
                "rows": sum(c["rows"] for c in moved),
                "epoch": epoch,
                "ingest_ts": oldest["ingest_ts"],
                "publish_ts": now,
                "stages": {
                    "queue_wait": oldest["queue_wait_s"],
                    "tick": oldest["tick_s"],
                    "publish": max(0.0, now - oldest["tick_end_ts"]),
                },
            }
            self._by_epoch[epoch] = ann
            while len(self._by_epoch) > self.max_epochs:
                self._by_epoch.popitem(last=False)
        return ann

    def flush_publish(self, ann: Optional[dict]) -> None:
        """Record the sealed epoch's writer stages: histogram samples, one
        ``e2e`` span per stage in the writer's ring, and timeline
        ``e2e_stage`` records for EXPLAIN SPIKE's stage detector."""
        if ann is None:
            return
        for stage in ("queue_wait", "tick", "publish"):
            self._record_stage(stage, ann["stages"][stage], ann["ids"],
                               spans=self._spans)

    def _record_stage(self, stage: str, seconds: float,
                      ids: List[str], spans=None) -> None:
        hist = self._hist
        if hist is not None:
            hist.labels(stage=stage).observe(seconds)
        if spans is not None:
            t1 = time.perf_counter_ns()
            spans.span_at("e2e:" + stage, t1 - int(seconds * 1e9), t1,
                          args={"trace": ids, "stage": stage,
                                "seconds": round(seconds, 6)})
        tl = self._timeline
        if tl is not None:
            tl.note_e2e_stage(stage, seconds, ids)

    # -- lookups ------------------------------------------------------------
    def for_epoch(self, epoch) -> Optional[dict]:
        """The sealed annotation for one published epoch (None once it has
        aged out of the bounded map, or for pre-tracing epochs)."""
        if not self.enabled or epoch is None:
            return None
        with self._lock:
            return self._by_epoch.get(epoch)

    def annotate_read(self, resp: dict, t0_perf: float) -> dict:
        """Attach ``age_s`` + per-stage breakdown to a primary ``/view``
        response (resolved from the response's epoch); observes the serve
        stage. Mutates and returns ``resp``."""
        if not self.enabled:
            return resp
        serve_s = max(0.0, time.perf_counter() - t0_perf)
        hist = self._hist
        if hist is not None:
            hist.labels(stage="serve").observe(serve_s)
        ann = self.for_epoch(resp.get("epoch"))
        if ann is not None:
            stages = dict(ann["stages"])
            stages["serve"] = serve_s
            resp["age_s"] = max(0.0, time.time() - ann["ingest_ts"])
            resp["stages"] = stages
            resp["trace"] = {"ids": list(ann["ids"])}
        return resp

    # -- replica-side path --------------------------------------------------
    def note_apply(self, ann: Optional[dict], recv_ts: float,
                   apply_s: float, spans=None) -> Optional[dict]:
        """Replica-side stage stamps for one applied changefeed record:
        extends the writer annotation (same trace ids) with transport =
        receipt - publish and the measured apply fold. ``spans`` is the
        *replica's* ring, so the same delta shows up in both processes'
        traces under identical ids."""
        if not self.enabled or ann is None:
            return None
        transport_s = max(0.0, recv_ts - ann.get("publish_ts", recv_ts))
        apply_s = max(0.0, apply_s)
        ids = list(ann.get("ids", ()))
        ext = dict(ann)
        stages = dict(ann.get("stages", {}))
        stages["transport"] = transport_s
        stages["apply"] = apply_s
        ext["stages"] = stages
        ext["applied_ts"] = recv_ts + apply_s
        # the stage spans go to the *replica's* ring, not the writer's
        self._record_stage("transport", transport_s, ids, spans=spans)
        self._record_stage("apply", apply_s, ids, spans=spans)
        return ext

    def annotate_replica_read(self, resp: dict, ext: Optional[dict],
                              t0_perf: float) -> dict:
        """Replica flavor of :meth:`annotate_read`: the stage breakdown
        comes from the stored applied annotation (which already includes
        transport/apply)."""
        if not self.enabled:
            return resp
        serve_s = max(0.0, time.perf_counter() - t0_perf)
        hist = self._hist
        if hist is not None:
            hist.labels(stage="serve").observe(serve_s)
        # epoch gate: a fold can land between the table snapshot and this
        # annotation — never label one epoch's rows with another's trace
        if ext is not None and ext.get("epoch") == resp.get("epoch"):
            stages = dict(ext["stages"])
            stages["serve"] = serve_s
            resp["age_s"] = max(0.0, time.time() - ext["ingest_ts"])
            resp["stages"] = stages
            resp["trace"] = {"ids": list(ext["ids"])}
        return resp

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled, "seq": self._seq,
                    "pending": len(self._pending),
                    "in_tick": len(self._in_tick),
                    "awaiting_publish": len(self._awaiting),
                    "epochs": len(self._by_epoch),
                    "dropped": self.dropped}
