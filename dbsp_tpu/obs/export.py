"""Prometheus text exposition (version 0.0.4) for metric registries.

The ONLY Prometheus-format string building in the tree lives here
(``tools/check_metrics.py`` lints the rest of ``dbsp_tpu/`` for strays):

* :func:`prometheus_text` — one registry, optional constant labels;
* :func:`prometheus_text_many` — the manager's fleet-wide aggregate: every
  pipeline's registry under a ``pipeline="<name>"`` label, one ``# TYPE``
  header per metric family across the fleet (reference:
  ``server/prometheus.rs`` per pipeline; the aggregate endpoint is ours);
* :func:`legacy_controller_lines` — the pre-registry metric names
  (``dbsp_steps``, ``dbsp_input_records`` ...) derived from controller
  stats, kept so existing scrapers/tests keep working.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from dbsp_tpu.obs.registry import (Histogram, Metric, MetricsRegistry,
                                   Summary, fmt_value)

CONTENT_TYPE = "text/plain; version=0.0.4"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _labelstr(names: Sequence[str], values: Sequence[str],
              extra: Sequence[Tuple[str, str]] = ()) -> str:
    pairs = [*zip(names, values), *extra]
    if not pairs:
        return ""
    inner = ",".join(f'{n}="{_escape(str(v))}"' for n, v in pairs)
    return "{" + inner + "}"


def _render_metric(metric: Metric, extra: Sequence[Tuple[str, str]],
                   lines: List[str], with_header: bool) -> None:
    if with_header:
        if metric.help:
            lines.append(f"# HELP {metric.name} {metric.help}")
        lines.append(f"# TYPE {metric.name} {metric.kind}")
    for values, child in metric.samples():
        if isinstance(metric, Summary):
            for q in metric.quantiles:
                ls = _labelstr(metric.label_names, values,
                               (*extra, ("quantile", str(q))))
                lines.append(f"{metric.name}{ls} "
                             f"{fmt_value(metric.quantile_of(child, q))}")
            base = _labelstr(metric.label_names, values, extra)
            lines.append(f"{metric.name}_sum{base} {fmt_value(child.sum)}")
            lines.append(f"{metric.name}_count{base} {child.count}")
        elif isinstance(metric, Histogram):
            cum = 0
            for bound, n in zip(metric.bounds, child.buckets):
                cum += n
                ls = _labelstr(metric.label_names, values,
                               (*extra, ("le", fmt_value(bound))))
                lines.append(f"{metric.name}_bucket{ls} {cum}")
            ls = _labelstr(metric.label_names, values,
                           (*extra, ("le", "+Inf")))
            lines.append(f"{metric.name}_bucket{ls} {child.count}")
            base = _labelstr(metric.label_names, values, extra)
            lines.append(f"{metric.name}_sum{base} {fmt_value(child.sum)}")
            lines.append(f"{metric.name}_count{base} {child.count}")
        else:
            ls = _labelstr(metric.label_names, values, extra)
            lines.append(f"{metric.name}{ls} {fmt_value(child.value)}")


def prometheus_text(registry: MetricsRegistry,
                    extra_labels: Optional[Dict[str, str]] = None) -> str:
    """Canonical exposition of one registry (collectors run first)."""
    extra = tuple((extra_labels or {}).items())
    lines: List[str] = []
    for metric in registry.collect():
        _render_metric(metric, extra, lines, with_header=True)
    return "\n".join(lines) + ("\n" if lines else "")


def prometheus_text_many(
        registries: Iterable[Tuple[Dict[str, str], MetricsRegistry]]) -> str:
    """Fleet-wide exposition: merge (constant_labels, registry) pairs so
    each metric family renders ONE header followed by every instance's
    samples — what the manager's aggregate ``/metrics`` serves."""
    collected: List[Tuple[Tuple[Tuple[str, str], ...], List[Metric]]] = [
        (tuple(labels.items()), reg.collect())
        for labels, reg in registries]
    families: Dict[str, List[Tuple[Tuple[Tuple[str, str], ...], Metric]]] = {}
    for extra, metrics in collected:
        for m in metrics:
            families.setdefault(m.name, []).append((extra, m))
    lines: List[str] = []
    for name in sorted(families):
        first = True
        for extra, m in families[name]:
            _render_metric(m, extra, lines, with_header=first)
            first = False
    return "\n".join(lines) + ("\n" if lines else "")


def legacy_controller_lines(stats: dict) -> List[str]:
    """The original ad-hoc per-pipeline metric names, derived from
    ``Controller.stats()`` — kept verbatim for scrapers written against the
    pre-registry surface (``dbsp_steps`` & co)."""
    lines = [
        "# TYPE dbsp_steps counter",
        f"dbsp_steps {stats['steps']}",
    ]
    for name, ep in stats["inputs"].items():
        ls = _labelstr(("endpoint",), (name,))
        lines.append(f"dbsp_input_records{ls} {ep['total_records']}")
        lines.append(f"dbsp_input_buffered{ls} {ep['buffered_records']}")
    for name, out in stats["outputs"].items():
        ls = _labelstr(("endpoint",), (name,))
        lines.append(f"dbsp_output_records{ls} {out['total_records']}")
    return lines
