"""Flight recorder: an always-on, bounded ring of structured engine events.

The bench harness (PR 3/4) could already attribute tail ticks —
``tick_causes``, ``host_overhead_ns``, ``spike_causes`` — but only as
post-hoc JSON from a bench run. A serving pipeline's latency spikes,
drains, overflow replays, and compiled->host fallbacks were "surfaced
nowhere a user would see them". This module promotes that attribution
machinery into a queryable subsystem: every pipeline keeps a small ring
buffer of structured events, fed from the same places ``instrument.py``
reads, and ``GET /flight`` dumps it on demand. "The Tail at Scale"
(Dean & Barroso, CACM 2013) is the design pressure: tail behavior is the
product metric, so the evidence for any tail sample must already be in
memory when someone asks.

Event kinds (one flat dict each; every event carries ``seq`` — a
monotone id — wall-clock ``ts`` and monotonic ``t_ns``):

  ``tick``            latency_ns, tick index, causes (maintain/snapshot/
                      retrace annotations — the spike-attribution channel)
  ``phase``           one between-tick host phase: phase=validate|
                      maintain|snapshot, ns
  ``maintain``        drain moves: rows_moved (+ drains/partial_drains on
                      the compiled path; merges/forced on the host path)
  ``overflow_replay`` one grow-and-replay cycle
  ``consolidate``     consolidation-regime dispatch deltas {path: n}
  ``exchange``        rows/bytes moved through shard/unshard this tick
  ``watermark``       event-time lag sample of a watermark operator
  ``compile``         a step-program (re)trace was observed
  ``fallback``        compiled->host fallback, with the recorded reason
  ``checkpoint``      one durable checkpoint generation written (tick,
                      generation, linked blob count) — or its failure
                      (``error``)
  ``restore``         a checkpoint restore: ``ok``, the restored tick, and
                      ``fallback_from`` when a corrupted newer generation
                      was skipped (the SLO watchdog turns these into
                      one-shot ``restore`` incidents; a failed restore
                      latches a degraded state). Cold-blob corruption
                      episodes (a disk-tier trace level failing its
                      digest at promotion, recovered from the newest
                      checkpoint generation recording the same hash)
                      ride the same kind with ``cold_blob`` set — one
                      SLO-visible incident per episode
  ``residency``       one trace-level residency transition (tiered trace
                      residency, dbsp_tpu/residency.py): node, level,
                      tier_from/tier_to, rows, and the cause (budget
                      demotion, maintain-drain promotion, fault-on-probe,
                      lru re-promotion, config/restore)
  ``transport``       terminal transport failure of an input endpoint
                      (dead broker past the retry budget) — latched by the
                      watchdog as a degraded state

Overhead discipline: ``record()`` is one dict build + deque append under a
lock — no device syncs, no formatting; tests/test_flight.py gates it at
< 2% of the q3 p50 tick time. The ring is bounded (default 2048 events),
so a serving pipeline can run forever with the recorder on.

Consumers: :class:`~dbsp_tpu.obs.slo.SLOWatchdog` evaluates SLOs over the
stream and freezes ring windows into incidents; ``bench.py`` replays the
same attribution (``spike_causes``) instead of private bookkeeping;
``/flight`` serves the raw ring.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, Iterable, List, Optional, Sequence, Tuple

from dbsp_tpu.testing.tsan import maybe_instrument as _tsan_hook

__all__ = [
    "FlightRecorder", "CompiledFlightSource", "HostFlightSource",
    "ControllerFlightSource",
    "spike_causes", "dominant_cause", "trace_slice", "ticks_from_samples",
]


class FlightRecorder:
    """Bounded ring of structured events; thread-safe, append-mostly."""

    def __init__(self, capacity: int = 2048):
        self.capacity = int(capacity)
        self._ring: Deque[dict] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.dropped = 0  # events aged out of the ring
        # drops by the EVICTED event's kind — a gap in the ring names
        # which source lost history instead of presenting as quiet time
        self.dropped_by_source: Dict[str, int] = {}
        _tsan_hook(self)

    def record(self, kind: str, t_ns: Optional[int] = None, **fields) -> int:
        """Append one event; returns its ``seq``. The hot-path cost budget
        is one dict + one deque append under the lock."""
        ev = {"kind": kind, "ts": time.time(),
              "t_ns": t_ns if t_ns is not None else time.perf_counter_ns()}
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            if len(self._ring) == self.capacity:
                self.dropped += 1
                src = self._ring[0].get("kind", "unknown")
                self.dropped_by_source[src] = \
                    self.dropped_by_source.get(src, 0) + 1
            self._ring.append(ev)
            return self._seq

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def events(self, kinds: Optional[Sequence[str]] = None,
               since_seq: int = 0,
               limit: Optional[int] = None) -> List[dict]:
        """Snapshot of ring events (oldest first), optionally filtered by
        kind, by ``seq > since_seq`` (incremental consumers), and capped to
        the most recent ``limit``."""
        with self._lock:
            out = list(self._ring)
        if since_seq:
            out = [e for e in out if e["seq"] > since_seq]
        if kinds is not None:
            ks = set(kinds)
            out = [e for e in out if e["kind"] in ks]
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def window(self, n: int = 128) -> List[dict]:
        """The most recent ``n`` events — what an incident freezes."""
        return self.events(limit=n)

    def drop_stats(self) -> Dict[str, int]:
        """Snapshot of per-source drop counts (evicted-event kinds)."""
        with self._lock:
            return dict(self.dropped_by_source)

    def to_dict(self, limit: Optional[int] = None) -> dict:
        with self._lock:
            dropped = self.dropped
            by_source = dict(self.dropped_by_source)
        return {"capacity": self.capacity, "dropped": dropped,
                "dropped_by_source": by_source,
                "truncated": dropped > 0,
                "last_seq": self.last_seq,
                "events": self.events(limit=limit)}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


# ---------------------------------------------------------------------------
# attribution helpers (shared by SLO incidents and bench.py)
# ---------------------------------------------------------------------------


def spike_causes(tick_events: Iterable[dict], spike_ns: float) -> Dict[str, int]:
    """Per-cause counts over spike ticks (latency above ``spike_ns``);
    unannotated spikes count as ``unattributed`` — the exact bookkeeping
    bench.py used to keep privately."""
    out: Dict[str, int] = {}
    for ev in tick_events:
        if ev.get("latency_ns", 0) > spike_ns:
            for cause in (ev.get("causes") or ("unattributed",)):
                out[cause] = out.get(cause, 0) + 1
    return out


def dominant_cause(tick_events: Sequence[dict],
                   p50_ns: Optional[float] = None
                   ) -> Tuple[str, Dict[str, int]]:
    """(dominant cause, per-cause counts) for a window of tick events.

    Preference order: causes annotated on SPIKE ticks (> 3x p50) when any
    exist — the ticks an SLO breach is actually about — otherwise causes on
    any annotated tick (timing noise must not flip attribution to
    ``unattributed`` when the window's only recorded activity is e.g. a
    maintain drain). Ties break toward the most recently seen cause."""
    ticks = list(tick_events)
    if p50_ns is None and ticks:
        lats = sorted(t.get("latency_ns", 0) for t in ticks)
        p50_ns = lats[len(lats) // 2]
    spikes = [t for t in ticks
              if p50_ns and t.get("latency_ns", 0) > 3 * p50_ns
              and t.get("causes")]
    pool = spikes or [t for t in ticks if t.get("causes")]
    counts: Dict[str, int] = {}
    last_seen: Dict[str, int] = {}
    for i, t in enumerate(pool):
        for c in t["causes"]:
            counts[c] = counts.get(c, 0) + 1
            last_seen[c] = i
    if not counts:
        return "unattributed", {}
    best = max(counts, key=lambda c: (counts[c], last_seen[c]))
    return best, counts


def trace_slice(events: Sequence[dict], pid: str = "dbsp_tpu") -> dict:
    """A Perfetto-loadable Chrome-trace rendering of a flight window.

    Ticks render as complete ("X") duration events on tid 0 — anchored at
    ``t_ns - latency_ns``, so batched compiled samples lay out back to
    back — host phases as "X" on tid 1, everything else as instant
    markers. Self-contained: an incident's ``trace`` key can be saved to a
    file and dropped into https://ui.perfetto.dev as-is."""
    tes: List[dict] = []
    for ev in events:
        t_us = ev["t_ns"] / 1e3
        if ev["kind"] == "tick":
            dur = ev.get("latency_ns", 0) / 1e3
            name = f"tick[{ev.get('tick', '?')}]"
            causes = ev.get("causes") or []
            tes.append({"name": name, "cat": "tick", "ph": "X",
                        "ts": t_us - dur, "dur": dur, "pid": pid, "tid": 0,
                        "args": {"causes": list(causes)}})
        elif ev["kind"] == "phase":
            dur = ev.get("ns", 0) / 1e3
            tes.append({"name": ev.get("phase", "phase"), "cat": "phase",
                        "ph": "X", "ts": t_us - dur, "dur": dur,
                        "pid": pid, "tid": 1})
        else:
            args = {k: v for k, v in ev.items()
                    if k not in ("kind", "ts", "t_ns", "seq")}
            tes.append({"name": ev["kind"], "cat": "event", "ph": "i",
                        "ts": t_us, "pid": pid, "tid": 2, "s": "t",
                        "args": args})
    return {"traceEvents": tes, "displayTimeUnit": "ms"}


def ticks_from_samples(flight: FlightRecorder, samples_ns: Sequence[int],
                       causes: Sequence[Tuple[int, str]] = ()) -> None:
    """Backfill tick events from a raw latency-sample list (host-mode
    bench runs, which have no live source attached)."""
    ann: Dict[int, List[str]] = {}
    for idx, cause in causes:
        ann.setdefault(idx, []).append(cause)
    now = time.perf_counter_ns()
    clock = now - sum(int(s) for s in samples_ns)
    for i, ns in enumerate(samples_ns):
        clock += int(ns)
        flight.record("tick", t_ns=clock, tick=i, latency_ns=int(ns),
                      causes=ann.get(i, []))


# ---------------------------------------------------------------------------
# sources: engine state -> ring events
# ---------------------------------------------------------------------------


class CompiledFlightSource:
    """Unseen-tail poller over a compiled driver (or bare CompiledHandle).

    Mirrors the scrape protocol of ``CompiledInstrumentation`` with its own
    cursors: ``step_times_ns``/``tick_causes`` become ``tick`` events,
    ``host_overhead_ns`` becomes ``phase`` events, ``overflow_replays`` and
    ``maintain_stats['rows_moved']`` deltas become ``overflow_replay`` /
    ``maintain`` events, and a ``retrace`` annotation also emits a
    ``compile`` marker. Poll sites: the controller's monitor hook (via
    ``PipelineObs.watch``) and any ``/flight``/``/incidents`` read."""

    def __init__(self, driver, flight: FlightRecorder):
        # bench holds a CompiledHandle directly; the serving path holds a
        # CompiledCircuitDriver whose .ch is the handle
        self.ch = getattr(driver, "ch", driver)
        self.flight = flight
        self._lock = threading.Lock()
        self._lat_seen = 0
        self._cause_seen = 0
        self._overhead_seen: Dict[str, int] = {}
        self._replays_seen = 0
        self._rows_moved_seen = 0
        self._consolidate_seen: Dict[str, int] = {}
        # residency transition log + cold-blob episode cursors (tiered
        # trace residency; the logs are append-only and never cleared by
        # reset_timing, so these cursors stay monotone)
        self._residency_seen = 0
        self._cold_seen = 0
        # synthetic wall anchors for batched samples (see trace_slice)
        self._clock_ns: Optional[int] = None
        _tsan_hook(self)

    def poll(self) -> None:
        ch = self.ch
        with self._lock:
            lat = getattr(ch, "step_times_ns", ())
            n = len(lat)
            if self._lat_seen > n:  # reset_timing() cleared the lists
                self._lat_seen = 0
                self._cause_seen = 0
                self._overhead_seen.clear()
                self._rows_moved_seen = 0  # maintain_stats zeroed too
            tail = list(lat[self._lat_seen:n])
            base_idx = self._lat_seen
            self._lat_seen = n
            causes = getattr(ch, "tick_causes", ())
            nc = len(causes)
            new_causes = list(causes[min(self._cause_seen, nc):nc])
            self._cause_seen = nc
            ann: Dict[int, List[str]] = {}
            for idx, cause in new_causes:
                ann.setdefault(idx, []).append(cause)
            if tail:
                now = time.perf_counter_ns()
                total = sum(int(s) for s in tail)
                clock = self._clock_ns if self._clock_ns is not None else 0
                clock = max(clock, now - total)
                for i, ns in enumerate(tail):
                    idx = base_idx + i
                    clock += int(ns)
                    cs = ann.pop(idx, [])
                    if "retrace" in cs:
                        self.flight.record("compile", t_ns=clock, tick=idx)
                    self.flight.record("tick", t_ns=clock, tick=idx,
                                       latency_ns=int(ns), causes=cs)
                self._clock_ns = clock
            # a concurrent scrape can observe a sample before its cause
            # annotation lands (_append_sample is not atomic across the two
            # lists) — late causes amend the already-emitted tick via a
            # tick_cause event the SLO watchdog folds back in
            for idx, cs in ann.items():
                self.flight.record("tick_cause", tick=idx, causes=cs)
            overhead = getattr(ch, "host_overhead_ns", None) or {}
            for phase, samples in overhead.items():
                np_ = len(samples)
                for ns in samples[self._overhead_seen.get(phase, 0):np_]:
                    self.flight.record("phase", phase=phase, ns=int(ns))
                self._overhead_seen[phase] = np_
            replays = getattr(ch, "overflow_replays", 0)
            for _ in range(replays - self._replays_seen):
                self.flight.record("overflow_replay")
            self._replays_seen = max(self._replays_seen, replays)
            stats = getattr(ch, "maintain_stats", None) or {}
            moved = stats.get("rows_moved", 0)
            if moved > self._rows_moved_seen:
                self.flight.record(
                    "maintain", rows_moved=moved - self._rows_moved_seen,
                    drains=stats.get("drains", 0),
                    partial_drains=stats.get("partial_drains", 0))
            self._rows_moved_seen = max(self._rows_moved_seen, moved)
            # residency transitions -> `residency` events; cold-blob
            # corruption episodes -> one-shot `restore` SLO incidents
            # (recovered=True episodes fell back to the checkpoint
            # generation's bytes; recovered=False latches degraded)
            rlog = getattr(ch, "residency_log", ())
            nr = len(rlog)
            for ev in list(rlog[self._residency_seen:nr]):
                self.flight.record("residency", **ev)
            self._residency_seen = nr
            clog = getattr(ch, "cold_events", ())
            ncold = len(clog)
            for ev in list(clog[self._cold_seen:ncold]):
                self.flight.record(
                    "restore", ok=bool(ev.get("recovered")),
                    cold_blob=ev.get("sha256", "")[:12],
                    fallback_from=ev.get("source"))
            self._cold_seen = ncold
            self._poll_consolidate()

    def _poll_consolidate(self) -> None:  # holds: _lock
        from dbsp_tpu.zset import kernels as zkernels

        delta = {}
        for path, count in zkernels.CONSOLIDATE_COUNTS.items():
            d = count - self._consolidate_seen.get(path, 0)
            if d > 0:
                delta[path] = d
            self._consolidate_seen[path] = count
        if delta:
            self.flight.record("consolidate", paths=delta)


class ControllerFlightSource:
    """IO-layer feeder: controller endpoint state -> ring events.

    Polls ``Controller.stats()`` (host dict reads, no device work) and
    records one ``transport`` event per endpoint-error TRANSITION — a dead
    broker or poisoned feed becomes SLO-visible (the watchdog latches it
    as a degraded state) instead of living only in /stats. Checkpoint
    events are recorded by the controller itself (``controller.flight``);
    this source only watches for failures the controller cannot announce
    synchronously."""

    def __init__(self, controller, flight: FlightRecorder):
        self.controller = controller
        self.flight = flight
        # poll() runs concurrently — PipelineObs.watch is both a scrape
        # collector (HTTP threads) and a controller monitor (circuit
        # thread); unlocked transition tracking would double-record
        # transport events (found by tools/check_concurrency.py C001)
        self._lock = threading.Lock()
        self._errors_seen: Dict[str, str] = {}
        _tsan_hook(self)

    def poll(self) -> None:
        try:
            stats = self.controller.stats()
        except Exception:
            return  # a mid-teardown race must not kill the watch pass
        with self._lock:
            for section in ("inputs", "outputs"):
                for name, ep in stats.get(section, {}).items():
                    err = ep.get("error")
                    key = f"{section}/{name}"
                    prev = self._errors_seen.get(key)
                    if err and prev != err:
                        self._errors_seen[key] = err
                        self.flight.record("transport", endpoint=name,
                                           error=str(err)[:200])
                    elif not err and prev:
                        # RECOVERY transition: a transient sink blip (the
                        # pending-batch retry delivered) must not leave
                        # the pipeline latched degraded forever
                        del self._errors_seen[key]
                        self.flight.record("transport", endpoint=name,
                                           recovered=True)


class HostFlightSource:
    """Host-path feeder: scheduler step events -> tick events with causes.

    Subscribes to the same ``SchedulerEvent`` stream as
    ``CircuitInstrumentation``. At each root-step end it records one tick
    event whose causes come from engine-state deltas gathered during the
    step: spine maintenance (``maintain_stats['merged_rows']`` across every
    spine in the graph) maps to cause ``maintain``, and exchange/watermark
    deltas become their own events. The graph walk is done ONCE at attach
    (operator sets are static post-build); per-tick cost is a handful of
    int reads."""

    def __init__(self, circuit, flight: FlightRecorder):
        from dbsp_tpu.timeseries.watermark import WatermarkMonotonic

        self.circuit = circuit
        self.flight = flight
        self._depth = 0
        self._step_t0: Optional[int] = None
        self._tick = 0
        self._spines: List[object] = []
        self._spine_nids: List[str] = []
        self._exchanges: List[object] = []
        self._wm_ops: List[object] = []
        for node in self._walk(circuit):
            op = node.operator
            sp = getattr(op, "spine", None)
            if sp is not None and hasattr(sp, "maintain_stats"):
                self._spines.append(sp)
                self._spine_nids.append(str(node.index))
            if op.name in ("shard", "unshard"):
                self._exchanges.append(op)
            if isinstance(op, WatermarkMonotonic):
                self._wm_ops.append(op)
        self._res_seen: List[int] = [
            len(getattr(sp, "residency_log", ())) for sp in self._spines]
        self._merged_seen = self._merged_rows()
        self._exch_seen = self._exchange_totals()
        self._wm_lag_seen: Dict[int, float] = {}
        _tsan_hook(self)
        circuit.register_scheduler_event_handler(self._on_event)

    @staticmethod
    def _walk(circuit):
        for node in circuit.nodes:
            yield node
            if node.child is not None:
                yield from HostFlightSource._walk(node.child)

    def _merged_rows(self) -> int:
        return sum(sp.maintain_stats.get("merged_rows", 0)
                   for sp in self._spines)

    def _exchange_totals(self) -> Tuple[int, int]:
        return (sum(getattr(op, "rows_moved", 0) for op in self._exchanges),
                sum(getattr(op, "bytes_moved", 0) for op in self._exchanges))

    def _on_event(self, ev) -> None:
        if ev.kind == "step_start":
            if self._depth == 0:
                self._step_t0 = ev.time_ns or time.perf_counter_ns()
            self._depth += 1
        elif ev.kind == "step_end":
            if self._depth == 0:
                return  # attached mid-step
            self._depth -= 1
            if self._depth or self._step_t0 is None:
                return
            t1 = ev.time_ns or time.perf_counter_ns()
            latency = t1 - self._step_t0
            self._step_t0 = None
            causes = []
            try:
                merged = self._merged_rows()
                if merged > self._merged_seen:
                    causes.append("maintain")
                    self.flight.record(
                        "maintain", t_ns=t1,
                        rows_moved=merged - self._merged_seen)
                self._merged_seen = merged
                rows, nbytes = self._exchange_totals()
                if rows > self._exch_seen[0] or nbytes > self._exch_seen[1]:
                    self.flight.record(
                        "exchange", t_ns=t1,
                        rows=rows - self._exch_seen[0],
                        bytes=nbytes - self._exch_seen[1])
                    self._exch_seen = (rows, nbytes)
                for i, op in enumerate(self._wm_ops):
                    if op._max_ts is None or op._last_batch_max is None:
                        continue
                    lag = op._max_ts - op._last_batch_max
                    if lag != self._wm_lag_seen.get(i):
                        self._wm_lag_seen[i] = lag
                        self.flight.record("watermark", t_ns=t1, lag=lag)
                # tiered-residency transitions (unseen-tail per spine)
                for i, sp in enumerate(self._spines):
                    rlog = getattr(sp, "residency_log", ())
                    n = len(rlog)
                    for ev in list(rlog[self._res_seen[i]:n]):
                        self.flight.record("residency", t_ns=t1,
                                           node=self._spine_nids[i], **ev)
                    self._res_seen[i] = n
            except Exception:
                pass  # a mid-step race must not kill the circuit thread
            self.flight.record("tick", t_ns=t1, tick=self._tick,
                               latency_ns=latency, causes=causes)
            self._tick += 1

    def poll(self) -> None:
        """No-op: the host source is push-driven by scheduler events."""
