"""Instrumentation: engine state -> registry metrics + trace spans.

Three attachment points, one per execution surface:

* :class:`CircuitInstrumentation` — host-driven circuits. Subscribes to the
  ``SchedulerEvent`` stream (the same stream ``CPUProfiler`` and
  ``TraceMonitor`` consume) for per-operator eval-latency histograms and
  step-latency summaries, and registers a scrape-time collector that walks
  the circuit graph for spine residency gauges, exchange counters, and
  watermark lag — state the operators already hold, read at scrape instead
  of copied per tick.
* :class:`CompiledInstrumentation` — compiled drivers. The whole tick is one
  XLA program, so per-operator timings do not exist; exports tick counters,
  tick-latency quantiles, overflow-replay counts, and per-trace
  device-resident capacity from the compiled states.
* :class:`ControllerInstrumentation` — the IO layer. Mirrors
  ``Controller.stats()`` endpoint counters into the registry at scrape.

:class:`PipelineObs` bundles one registry + one span recorder per deployed
pipeline (the unit the manager aggregates over).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple

from dbsp_tpu.obs.registry import MetricsRegistry
from dbsp_tpu.obs.tracing import SpanRecorder

# span categories for the trace viewer; exchange ops get their own so
# cross-worker data movement is visually separable from compute
_EXCHANGE_OPS = ("shard", "unshard")


def export_consolidate_paths(registry: MetricsRegistry) -> None:
    """Register a collector mirroring the consolidation-regime counters
    (``zset/kernels.py::CONSOLIDATE_COUNTS``) as
    ``dbsp_tpu_zset_consolidate_total{path=sort|rank|native|skipped|deferred}``.

    The counts are PROCESS-wide dispatch decisions (eager calls count per
    eval, traced calls once per trace, deferrals once per placement pass) —
    they attribute WHICH consolidation regimes fire, not per-tick volume."""
    if getattr(registry, "_consolidate_paths_exported", False):
        return  # one mirror per registry (both instrumentations may share)
    registry._consolidate_paths_exported = True
    counter = registry.counter(
        "dbsp_tpu_zset_consolidate_total",
        "Consolidation dispatch decisions by regime (process-wide; "
        "skipped = metadata no-op, rank = sorted-run merge fold, "
        "native = C++ argsort, sort = lax.sort, "
        "native_unsupported_dtype = native selected but demoted to sort "
        "by a non-int64-widenable column dtype, deferred = removed by "
        "the compiled placement pass)", labels=("path",))

    def _collect() -> None:
        from dbsp_tpu.zset import kernels as zkernels

        for path, n in zkernels.CONSOLIDATE_COUNTS.items():
            counter.labels(path=path).set_total(n)

    registry.register_collector(_collect)


def export_kernel_dispatch(registry: MetricsRegistry) -> None:
    """Register a collector mirroring the kernel dispatch decisions
    (``zset/kernels.py::KERNEL_DISPATCH_COUNTS``) as
    ``dbsp_tpu_zset_kernel_dispatch_total{kernel,backend}`` — which
    implementation (native C++ custom call / pure XLA / Pallas) each Z-set
    kernel entry point selected. Same counting convention as the
    consolidation-path counter: dispatch DECISIONS (per eval eagerly, per
    trace under jit), not per-tick kernel volume — the metric answers "is
    this pipeline on the kernels I think it is", e.g. after a
    ``DBSP_TPU_NATIVE`` force-off or a dtype change knocked a path off the
    native set."""
    if getattr(registry, "_kernel_dispatch_exported", False):
        return
    registry._kernel_dispatch_exported = True
    counter = registry.counter(
        "dbsp_tpu_zset_kernel_dispatch_total",
        "Z-set kernel dispatch decisions by entry point and backend "
        "(native = C++ FFI custom call, xla = pure-XLA lowering, "
        "pallas = hand-written Pallas program); the fused ladder-consumer "
        "megakernels report as kernel=join_ladder / gather_ladder / "
        "old_weights and the reduction offensive as kernel=segment_reduce "
        "/ agg_ladder / join_sorted, whose xla rows are the stitched-chain "
        "fallback (the DBSP_TPU_NATIVE force-off A/B control)",
        labels=("kernel", "backend"))

    def _collect() -> None:
        from dbsp_tpu.zset import kernels as zkernels

        for (kern, backend), n in list(
                zkernels.KERNEL_DISPATCH_COUNTS.items()):
            counter.labels(kernel=kern, backend=backend).set_total(n)

    registry.register_collector(_collect)


def export_exchange_overflows(registry: MetricsRegistry) -> None:
    """Register a collector mirroring the process-wide exchange
    bucket-overflow detections (``parallel/exchange.py::
    EXCHANGE_OVERFLOW_COUNTS``) as
    ``dbsp_tpu_exchange_overflow_total{kind}``: each count is one validated
    interval whose per-worker exchange (or sharded-input) bucket overflowed
    under skew and was re-run at grown capacity by the overflow-replay
    machinery — the replay saves the rows; the counter makes it visible."""
    if getattr(registry, "_exchange_overflows_exported", False):
        return
    registry._exchange_overflows_exported = True
    counter = registry.counter(
        "dbsp_tpu_exchange_overflow_total",
        "Exchange bucket overflows detected by the capacity-requirement "
        "check and repaired by overflow replay (kind = exchange | input)",
        labels=("kind",))

    def _collect() -> None:
        from dbsp_tpu.parallel.exchange import EXCHANGE_OVERFLOW_COUNTS

        for kind, n in list(EXCHANGE_OVERFLOW_COUNTS.items()):
            counter.labels(kind=kind).set_total(n)

    registry.register_collector(_collect)


def _gid_str(gid: Tuple[int, ...]) -> str:
    return ".".join(map(str, gid))


def _residency_tier_gauge(reg: MetricsRegistry, nid: str,
                          tiers: Dict[str, int]) -> None:
    """Per-tier resident rows of one trace (both engines share the
    family; tier names come from dbsp_tpu/residency.py)."""
    tier_gauge = reg.gauge(
        "dbsp_tpu_trace_tier_resident_rows",
        "Resident row capacity of one trace per residency tier (device = "
        "persistent HBM/device buffers, host = process-resident numpy, "
        "disk = memmap views over cold-store blobs; see "
        "dbsp_tpu/residency.py)", labels=("node", "tier"))
    for tier, rows in tiers.items():
        tier_gauge.labels(node=nid, tier=tier).set(rows)


def _residency_transitions(reg: MetricsRegistry,
                           agg: Dict[Tuple[str, str, str], int]) -> None:
    """Cumulative transition counts summed over every trace this
    instrumentation covers — the demotion/promotion evidence the growth
    acceptance reads. Called once per collect pass (set_total semantics:
    per-node stats must be pre-aggregated by the caller)."""
    if not agg:
        return
    trans = reg.counter(
        "dbsp_tpu_trace_residency_transitions_total",
        "Residency tier transitions by direction and cause (budget = "
        "enforcement demotion, maintain = drain-write promotion, probe = "
        "fault-on-probe promotion, lru = re-hot promotion, "
        "config/restore = applied at deploy/restore)",
        labels=("tier_from", "tier_to", "cause"))
    for (frm, to, cause), n in agg.items():
        trans.labels(tier_from=frm, tier_to=to, cause=cause).set_total(n)


class CircuitInstrumentation:
    """Host-path hooks: scheduler events -> histograms/spans, graph walk ->
    gauges. Attach once per circuit, after build."""

    def __init__(self, circuit, registry: MetricsRegistry,
                 spans: Optional[SpanRecorder] = None):
        self.circuit = circuit
        self.registry = registry
        self.spans = spans
        self._open: Dict[Tuple[int, ...], int] = {}
        self._step_t0: Optional[int] = None
        self._depth = 0
        self._names: Dict[Tuple[int, ...], str] = {}
        self.eval_hist = registry.histogram(
            "dbsp_tpu_circuit_operator_eval_seconds",
            "Host wall-clock of one operator eval (includes kernel "
            "dispatch; see profile.py for the async caveat)",
            labels=("operator", "node"))
        self.step_summary = registry.summary(
            "dbsp_tpu_circuit_step_seconds",
            "End-to-end latency of one root-circuit step")
        self.steps_total = registry.counter(
            "dbsp_tpu_circuit_steps_total", "Root-circuit steps evaluated")
        registry.register_collector(self._collect_graph)
        export_consolidate_paths(registry)
        export_kernel_dispatch(registry)
        export_exchange_overflows(registry)
        circuit.register_scheduler_event_handler(self._on_event)
        # mark exchange operators so they accumulate rows/bytes moved —
        # this costs one scalar device->host sync per exchange per tick
        # (shard_op._MovedRowsMixin), so it is env-gated for latency-
        # critical deploys: DBSP_TPU_OBS_EXCHANGE=0 keeps the counters off
        if os.environ.get("DBSP_TPU_OBS_EXCHANGE", "1") != "0":
            for node, _ in self._walk():
                if node.operator.name in _EXCHANGE_OPS:
                    node.operator.obs_enabled = True

    # -- event path ---------------------------------------------------------
    def _on_event(self, ev) -> None:
        if ev.kind == "eval_start":
            ts = ev.time_ns or time.perf_counter_ns()
            self._open[ev.node_id] = ts
            self._names[ev.node_id] = ev.name or "?"
            if self.spans is not None and self._depth:
                cat = "exchange" if ev.name in _EXCHANGE_OPS else "operator"
                self.spans.begin(f"{ev.name}[{_gid_str(ev.node_id)}]",
                                 cat=cat, ts_ns=ts)
        elif ev.kind == "eval_end":
            t0 = self._open.pop(ev.node_id, None)
            ts = ev.time_ns or time.perf_counter_ns()
            if t0 is not None:
                self.eval_hist.labels(
                    operator=ev.name or self._names.get(ev.node_id, "?"),
                    node=_gid_str(ev.node_id)).observe((ts - t0) / 1e9)
            if self.spans is not None and self._depth:
                self.spans.end(f"{ev.name}[{_gid_str(ev.node_id)}]",
                               ts_ns=ts)
        elif ev.kind == "step_start":
            ts = ev.time_ns or time.perf_counter_ns()
            if self._depth == 0:
                self._step_t0 = ts
            self._depth += 1
            if self.spans is not None:
                self.spans.begin("step" if self._depth == 1 else "substep",
                                 cat="step", ts_ns=ts)
        elif ev.kind == "step_end":
            ts = ev.time_ns or time.perf_counter_ns()
            if self._depth > 0:
                self._depth -= 1
                if self.spans is not None:
                    self.spans.end("step" if self._depth == 0 else "substep",
                                   ts_ns=ts)
                if self._depth == 0 and self._step_t0 is not None:
                    self.step_summary.observe((ts - self._step_t0) / 1e9)
                    self.steps_total.inc()
                    self._step_t0 = None

    # -- scrape-time graph walk ----------------------------------------------
    def _walk(self, circuit=None, prefix=()):
        c = circuit if circuit is not None else self.circuit
        for node in c.nodes:
            gid = (*prefix, node.index)
            yield node, gid
            if node.child is not None:
                yield from self._walk(node.child, gid)

    def _collect_graph(self) -> None:
        from dbsp_tpu.operators.trace_op import TraceOp
        from dbsp_tpu.timeseries.watermark import WatermarkMonotonic

        reg = self.registry
        res_trans: Dict[Tuple[str, str, str], int] = {}
        for node, gid in self._walk():
            op = node.operator
            nid = _gid_str(gid)
            try:
                if isinstance(op, TraceOp):
                    sp = op.spine
                    reg.gauge("dbsp_tpu_trace_device_resident_rows",
                              "Device (HBM) resident row capacity of one "
                              "spine (sharded batches count per-worker cap; "
                              "see trace/spine.py budget semantics)",
                              labels=("node",)).labels(node=nid).set(
                                  sp.device_resident_rows())
                    reg.gauge("dbsp_tpu_trace_host_offloaded_rows",
                              "Row capacity offloaded to host memory "
                              "(cold levels)",
                              labels=("node",)).labels(node=nid).set(
                                  sp.host_offloaded_rows())
                    _residency_tier_gauge(reg, nid, sp.tier_rows())
                    for k, n in sp.residency_stats.items():
                        res_trans[k] = res_trans.get(k, 0) + n
                    reg.gauge("dbsp_tpu_trace_level_count",
                              "Spine LSM levels currently held",
                              labels=("node",)).labels(node=nid).set(
                                  len(sp.batches))
                elif op.name in _EXCHANGE_OPS:
                    reg.counter("dbsp_tpu_exchange_rows_total",
                                "Live rows moved through shard/unshard "
                                "exchanges", labels=("node",)).labels(
                                    node=nid).set_total(
                                        getattr(op, "rows_moved", 0))
                    reg.counter("dbsp_tpu_exchange_bytes_total",
                                "Bytes moved through shard/unshard "
                                "exchanges", labels=("node",)).labels(
                                    node=nid).set_total(
                                        getattr(op, "bytes_moved", 0))
                    occ = getattr(op, "last_occupancy", None)
                    if occ and len(occ) > 1:
                        occ_gauge = reg.gauge(
                            "dbsp_tpu_exchange_worker_occupancy_rows",
                            "Live rows landed on each worker by the last "
                            "observed exchange eval (the skew input)",
                            labels=("node", "worker"))
                        for wi, n in enumerate(occ):
                            occ_gauge.labels(node=nid,
                                             worker=str(wi)).set(n)
                        reg.gauge(
                            "dbsp_tpu_exchange_skew_ratio",
                            "Max/mean worker occupancy of the last "
                            "observed exchange eval (1.0 = balanced, "
                            "W = one worker holds everything)",
                            labels=("node",)).labels(node=nid).set(
                                op.skew_ratio)
                elif isinstance(op, WatermarkMonotonic):
                    if op._wm is not None:
                        reg.gauge("dbsp_tpu_timeseries_watermark_timestamp",
                                  "Current watermark (event-time units)",
                                  labels=("node",)).labels(node=nid).set(
                                      op._wm)
                        # lag = how far the latest batch's events trail
                        # the event-time frontier (0 for in-order arrival,
                        # grows when a batch is older than the max seen).
                        # NOT frontier-minus-watermark: that is identically
                        # the configured lateness here and carries no
                        # signal. Both fields can be None (no batch yet /
                        # restored checkpoint) — skip the gauge then.
                        if op._max_ts is not None and \
                                op._last_batch_max is not None:
                            reg.gauge(
                                "dbsp_tpu_timeseries_watermark_lag_count",
                                "Event-time lag of the latest batch "
                                "behind the frontier (max seen minus "
                                "latest batch max, event-time units)",
                                labels=("node",)).labels(node=nid).set(
                                    op._max_ts - op._last_batch_max)
            except Exception:
                # scrape must not take the server down on a mid-step race;
                # the next scrape sees a consistent value
                continue
        try:
            _residency_transitions(reg, res_trans)
        except Exception:
            pass  # same scrape-safety posture as the walk above


class CompiledInstrumentation:
    """Compiled-path hooks: collector over the driver + compiled states."""

    def __init__(self, driver, registry: MetricsRegistry,
                 spans: Optional[SpanRecorder] = None):
        self.driver = driver
        self.registry = registry
        self._lat_seen = 0
        # the pipeline server and the manager's fleet aggregate can scrape
        # the same registry concurrently; the tail-consume below is a
        # read-modify-write that would double-observe without this
        self._lat_lock = threading.Lock()
        self.tick_summary = registry.summary(
            "dbsp_tpu_compiled_tick_seconds",
            "Whole-tick latency of the compiled step program")
        self.ticks_total = registry.counter(
            "dbsp_tpu_compiled_ticks_total", "Compiled ticks run")
        self.replays_total = registry.counter(
            "dbsp_tpu_compiled_overflow_replays_total",
            "Grow-and-replay cycles after a capacity overflow")
        # between-tick host phases (validate fetch / maintain drains /
        # snapshot copies) — the wall-clock the async tick pipeline exists
        # to bound; a spike tick's cause annotations are counted per cause
        self.host_overhead_hist = registry.histogram(
            "dbsp_tpu_compiled_tick_host_overhead_seconds",
            "Host wall-clock of one between-tick phase of the compiled "
            "step loop (validate = the per-interval device fetch, "
            "maintain = bounded LSM drain slice, snapshot = incremental "
            "state copy)", labels=("phase",))
        self.causes_total = registry.counter(
            "dbsp_tpu_compiled_tick_causes_total",
            "Latency-sample annotations by cause (maintain drain, "
            "snapshot copy, program retrace) — attributes tail ticks",
            labels=("cause",))
        self.maintain_rows_total = registry.counter(
            "dbsp_tpu_compiled_maintain_moved_rows_total",
            "Rows moved between trace levels by bounded maintenance")
        self._overhead_seen: Dict[str, int] = {}
        registry.register_collector(self._collect)
        export_consolidate_paths(registry)
        export_kernel_dispatch(registry)
        export_exchange_overflows(registry)
        if spans is not None:
            driver.spans = spans  # driver records tick/validate spans

    def _collect(self) -> None:
        from dbsp_tpu.compiled import cnodes

        d = self.driver
        self.ticks_total.set_total(getattr(d, "_tick", 0))
        # step_latencies_ns is the driver's live append-only list; slice
        # only the unseen tail (a full copy would be O(total ticks) per
        # scrape, unbounded on a serving pipeline)
        lat = getattr(d, "step_latencies_ns", ())
        with self._lat_lock:
            n = len(lat)
            tail = lat[self._lat_seen:n]
            self._lat_seen = n
        for ns in tail:
            self.tick_summary.observe(ns / 1e9)
        ch = getattr(d, "ch", None)
        if ch is None:
            return
        self.replays_total.set_total(getattr(ch, "overflow_replays", 0))
        # host-overhead phases: same unseen-tail protocol as latencies
        overhead = getattr(ch, "host_overhead_ns", None)
        if overhead:
            with self._lat_lock:
                for phase, samples in overhead.items():
                    n = len(samples)
                    tail = samples[self._overhead_seen.get(phase, 0):n]
                    self._overhead_seen[phase] = n
                    child = self.host_overhead_hist.labels(phase=phase)
                    for ns in tail:
                        child.observe(ns / 1e9)
        causes: Dict[str, int] = {}
        for _, cause in getattr(ch, "tick_causes", ()):
            causes[cause] = causes.get(cause, 0) + 1
        for cause, count in causes.items():
            self.causes_total.labels(cause=cause).set_total(count)
        stats = getattr(ch, "maintain_stats", None)
        if stats:
            self.maintain_rows_total.set_total(stats.get("rows_moved", 0))
        # ONE walk for all traces' tier partitions (per-key tier_rows
        # calls would re-walk every leveled node per node — O(N^2) per
        # scrape)
        tiers_by_node = (ch.tier_rows_by_node()
                         if hasattr(ch, "tier_rows_by_node") else {})
        for cn in ch.cnodes:
            if isinstance(cn, cnodes.CExchange):
                # compiled skew observable: worst-worker rows at the last
                # validation vs the static per-worker bucket (occupancy
                # near 1.0 = the next skewed tick overflows and replays)
                nid = str(cn.node.index)
                cap = cn.caps.get("exchange", 0)
                self.registry.gauge(
                    "dbsp_tpu_exchange_required_rows",
                    "Worst-worker live rows through this compiled "
                    "exchange at the last validation",
                    labels=("node",)).labels(node=nid).set(
                        cn.last_required)
                if cap:
                    self.registry.gauge(
                        "dbsp_tpu_exchange_bucket_occupancy_ratio",
                        "last_required / static per-worker exchange "
                        "capacity (>= 1.0 would overflow and replay)",
                        labels=("node",)).labels(node=nid).set(
                            cn.last_required / cap)
            if not isinstance(cn, cnodes._Leveled):
                continue
            nid = str(cn.node.index)
            # tiered residency (dbsp_tpu/residency.py): deep levels past
            # the budget live as host numpy / disk memmaps — the device
            # gauge reports the DEVICE tier only, the per-tier gauge
            # carries the full picture
            tiers = tiers_by_node.get(nid)
            if tiers is not None:
                self.registry.gauge(
                    "dbsp_tpu_trace_device_resident_rows",
                    "Device-resident row capacity of one compiled "
                    "leveled trace (device tier only — residency-"
                    "demoted levels are excluded)",
                    labels=("node",)).labels(node=nid).set(
                        tiers["device"])
                _residency_tier_gauge(self.registry, nid, tiers)
                self.registry.gauge(
                    "dbsp_tpu_trace_host_offloaded_rows",
                    "Row capacity offloaded to host memory "
                    "(cold levels)",
                    labels=("node",)).labels(node=nid).set(tiers["host"])
            self.registry.gauge(
                "dbsp_tpu_trace_level_count",
                "Levels of one compiled leveled trace",
                labels=("node",)).labels(node=nid).set(len(cn.level_keys))
        if hasattr(ch, "residency_stats"):
            try:
                _residency_transitions(
                    self.registry,
                    {k: n for k, n in list(ch.residency_stats.items())})
            except Exception:
                pass  # scrape-safety: never take the server down


class ControllerInstrumentation:
    """IO-layer mirror: Controller.stats() -> registry, at scrape time."""

    def __init__(self, controller, registry: MetricsRegistry):
        self.controller = controller
        self.registry = registry
        registry.register_collector(self._collect)

    def _collect(self) -> None:
        reg = self.registry
        s = self.controller.stats()
        reg.counter("dbsp_tpu_io_steps_total",
                    "Controller-driven circuit steps").set_total(s["steps"])
        reg.counter("dbsp_tpu_io_pushed_records_total",
                    "Rows pushed via the host API / HTTP endpoints"
                    ).set_total(s["pushed_records"])
        reg.counter("dbsp_tpu_io_checkpoints_total",
                    "Durable checkpoint generations written by this "
                    "controller").set_total(s.get("checkpoints", 0))
        # (the tick the last checkpoint covers is NOT a metric — it is an
        # index, not a count/unit; read it from /status or /stats)
        for name, ep in s["inputs"].items():
            reg.counter("dbsp_tpu_io_transport_retries_total",
                        "Transient transport failures retried with "
                        "backoff (connect/read), per input endpoint",
                        labels=("endpoint",)).labels(
                            endpoint=name).set_total(
                                ep.get("transport_retries", 0))
            reg.counter("dbsp_tpu_io_input_records_total",
                        "Rows ingested per input endpoint",
                        labels=("endpoint",)).labels(
                            endpoint=name).set_total(ep["total_records"])
            reg.counter("dbsp_tpu_io_input_bytes_total",
                        "Bytes ingested per input endpoint",
                        labels=("endpoint",)).labels(
                            endpoint=name).set_total(ep["total_bytes"])
            reg.gauge("dbsp_tpu_io_input_buffered_rows",
                      "Rows buffered awaiting a step",
                      labels=("endpoint",)).labels(
                          endpoint=name).set(ep["buffered_records"])
        for name, out in s["outputs"].items():
            reg.counter("dbsp_tpu_io_output_records_total",
                        "Rows emitted per output endpoint",
                        labels=("endpoint",)).labels(
                            endpoint=name).set_total(out["total_records"])
            reg.counter("dbsp_tpu_io_output_bytes_total",
                        "Bytes emitted per output endpoint",
                        labels=("endpoint",)).labels(
                            endpoint=name).set_total(out["total_bytes"])


class PipelineObs:
    """Per-pipeline observability bundle: one registry + one span window +
    one flight recorder + one SLO watchdog.

    Construction wires nothing; call the ``attach_*`` helpers for the
    surfaces the pipeline actually runs (host circuit, compiled driver,
    controller). The manager aggregates ``(labels, registry)`` pairs from
    every deployed pipeline into the fleet-wide exposition and the
    per-pipeline SLO states into fleet health.

    ``slo`` is the pipeline config's ``slo`` section (obs/slo.py config
    keys); the watchdog runs with every key disabled except the
    host-fallback one when omitted. :meth:`watch` — one poll of every
    flight source plus one SLO evaluation — is registered as a scrape-time
    collector and as a controller monitor, so SLO state is fresh on both
    paths without a dedicated thread."""

    def __init__(self, name: str = "",
                 max_trace_steps: Optional[int] = None,
                 flight_capacity: int = 2048, slo=None):
        from dbsp_tpu.obs.flight import FlightRecorder
        from dbsp_tpu.obs.slo import SLOConfig, SLOWatchdog
        from dbsp_tpu.obs.timeline import Timeline

        self.name = name
        self.registry = MetricsRegistry()
        # span-ring window: DBSP_TPU_TRACE_STEPS tunes the retained
        # top-level span count (the /trace window); evictions export as
        # dbsp_tpu_obs_trace_dropped_total{pipeline} via bind()
        if max_trace_steps is None:
            max_trace_steps = int(os.environ.get("DBSP_TPU_TRACE_STEPS",
                                                 "64"))
        self.spans = SpanRecorder(max_steps=max_trace_steps,
                                  process=name or "dbsp_tpu")
        self.spans.bind(self.registry, pipeline=name)
        self.flight = FlightRecorder(capacity=flight_capacity)
        self.slo = SLOWatchdog(self.flight, SLOConfig.from_dict(slo),
                               registry=self.registry, pipeline=name)
        # unified per-tick timeline: flight events + SLO incidents + tick
        # records + freshness stamps in one time-indexed ring (the spike
        # attribution and staleness surfaces read it)
        self.timeline = Timeline(registry=self.registry, pipeline=name)
        self._flight_sources = []
        self.registry.register_collector(self.watch)

    def watch(self):
        """One watchdog pass: poll flight sources, evaluate SLOs, and fold
        the fresh flight events + any newly opened incidents into the
        timeline. Returns the incidents opened by this pass."""
        for src in self._flight_sources:
            src.poll()
        incidents = self.slo.evaluate()
        self.timeline.ingest_flight(self.flight)
        for inc in incidents or ():
            self.timeline.note_incident(inc)
        return incidents

    def attach_circuit(self, circuit) -> CircuitInstrumentation:
        from dbsp_tpu.obs.flight import HostFlightSource

        self._flight_sources.append(HostFlightSource(circuit, self.flight))
        return CircuitInstrumentation(circuit, self.registry,
                                      spans=self.spans)

    def attach_compiled(self, driver) -> CompiledInstrumentation:
        from dbsp_tpu.obs.flight import CompiledFlightSource

        self._flight_sources.append(CompiledFlightSource(driver,
                                                         self.flight))
        return CompiledInstrumentation(driver, self.registry,
                                       spans=self.spans)

    def attach_controller(self, controller) -> ControllerInstrumentation:
        from dbsp_tpu.obs.flight import ControllerFlightSource

        add_monitor = getattr(controller, "add_monitor", None)
        if add_monitor is not None:
            add_monitor(self.watch)
        # checkpoint/restore events become SLO-visible: the controller
        # records them on this pipeline's ring, and the flight source
        # watches endpoint/transport failures the controller cannot
        # announce synchronously
        if hasattr(controller, "flight"):
            controller.flight = self.flight
        # tick latency + freshness stamps: the controller writes tick and
        # arrival/visibility records straight onto this pipeline's timeline
        if hasattr(controller, "timeline"):
            controller.timeline = self.timeline
        # read serving plane (dbsp_tpu/serving.py): read QPS/latency
        # metrics + a flight ring for staleness-breach attribution
        plane = getattr(controller, "read_plane", None)
        if plane is not None:
            plane.bind(registry=self.registry, flight=self.flight)
        # fleet-wide delta tracing (obs/tracing.py): the controller's
        # E2ETracer exports dbsp_tpu_e2e_stage_seconds{stage}, records
        # per-stage spans into this pipeline's ring, and feeds the
        # timeline's e2e_stage stream (EXPLAIN SPIKE stage attribution)
        e2e = getattr(controller, "e2e", None)
        if e2e is not None:
            e2e.bind(registry=self.registry, spans=self.spans,
                     timeline=self.timeline)
        self._flight_sources.append(
            ControllerFlightSource(controller, self.flight))
        return ControllerInstrumentation(controller, self.registry)
