"""``dbsp_tpu.obs`` — the unified metrics & tracing subsystem.

One coherent observability layer over the engine (reference:
``profile/cpu.rs`` + ``circuit/metadata.rs`` + ``server/prometheus.rs`` +
the pipeline-manager's per-pipeline stats, ``dbsp_handle.rs:256-268``):

* :mod:`dbsp_tpu.obs.registry` — labeled counters / gauges / log-bucketed
  histograms / quantile summaries in a :class:`MetricsRegistry`;
* :mod:`dbsp_tpu.obs.export` — canonical Prometheus text exposition
  (single-registry and fleet-wide multi-pipeline aggregation) — the ONLY
  place in the tree that formats Prometheus text (tools/check_metrics.py
  enforces this);
* :mod:`dbsp_tpu.obs.tracing` — a bounded-window span recorder emitting
  Chrome-trace-format JSON with real pid/tid lanes (load the export in
  Perfetto / chrome://tracing), plus the fleet-wide end-to-end delta
  tracer (:class:`E2ETracer`): per-batch trace contexts flowing
  ingest→tick→publish→changefeed→replica→read, stage-attributed into
  ``dbsp_tpu_e2e_stage_seconds{stage}`` and merged across processes by
  ``merge_chrome_traces`` (manager ``GET /fleet/trace``);
* :mod:`dbsp_tpu.obs.instrument` — hooks subscribing to the circuit's
  ``SchedulerEvent`` stream (host path) or polling a compiled driver
  (compiled path), publishing per-operator eval histograms, step latency,
  spine residency gauges, exchange counters, watermark lag;
* :mod:`dbsp_tpu.obs.flight` — the always-on flight recorder: a bounded
  ring of structured engine events (per-tick latency with cause, host
  phases, drains, replays, fallbacks) served at ``/flight``;
* :mod:`dbsp_tpu.obs.slo` — the SLO watchdog: configurable objectives
  evaluated in the controller loop; breaches freeze ring windows into
  cause-attributed incidents served at ``/incidents``;
* :mod:`dbsp_tpu.obs.timeline` — the unified per-tick timeline: tick
  records + flight events + freshness samples + incidents in one bounded
  time-indexed ring, with EXPLAIN SPIKE attribution (``/timeline``,
  ``/spikes``) and the ``dbsp_tpu_freshness_seconds{view}`` export.

Metric names follow ``dbsp_tpu_<subsystem>_<name>_<unit>`` (see
``registry.validate_metric_name``); the catalog lives in README.md
§Observability.
"""

from dbsp_tpu.obs.export import (legacy_controller_lines, prometheus_text,
                                 prometheus_text_many)
from dbsp_tpu.obs.flight import FlightRecorder
from dbsp_tpu.obs.instrument import (CircuitInstrumentation,
                                     CompiledInstrumentation,
                                     ControllerInstrumentation, PipelineObs)
from dbsp_tpu.obs.registry import (Counter, Gauge, Histogram,
                                   MetricNameError, MetricsRegistry, Summary,
                                   validate_metric_name)
from dbsp_tpu.obs.slo import SLOConfig, SLOWatchdog
from dbsp_tpu.obs.timeline import SPIKE_CAUSES, Timeline
from dbsp_tpu.obs.tracing import (E2E_STAGES, E2ETracer, SpanRecorder,
                                  merge_chrome_traces, trace_e2e_enabled)

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "Summary",
    "MetricNameError", "validate_metric_name",
    "prometheus_text", "prometheus_text_many", "legacy_controller_lines",
    "SpanRecorder", "FlightRecorder", "SLOConfig", "SLOWatchdog",
    "E2ETracer", "E2E_STAGES", "trace_e2e_enabled", "merge_chrome_traces",
    "Timeline", "SPIKE_CAUSES",
    "CircuitInstrumentation", "CompiledInstrumentation",
    "ControllerInstrumentation", "PipelineObs",
]
