"""SLO watchdog: breach detection + incident capture over a flight ring.

The serving promise of a DBSP pipeline is cost-proportional-to-delta; the
failure modes that break it — drain/re-trace latency spikes, compiled->host
fallbacks (an order-of-magnitude perf cliff), watermark lag, overflow
replays — each have a configured objective here. The watchdog evaluates in
the controller/monitor loop (``Controller.add_monitor`` ->
``PipelineObs.watch``) and at every scrape; on breach it freezes the
surrounding :class:`~dbsp_tpu.obs.flight.FlightRecorder` window into a
self-contained **incident**: a JSON report carrying the attributed dominant
cause (the same ``tick_causes`` channel bench.py reports) plus a
Perfetto-loadable trace slice of the window.

Config keys (``SLOConfig``; pipeline config section ``slo`` or env
``DBSP_TPU_SLO_*`` for harnesses):

  ``p99_tick_seconds``     rolling-window p99 tick latency bound
  ``tick_p50_multiple``    absolute per-tick bound as k x rolling p50
                           (the tail-amplification objective: p99/p50 was
                           the PR-3 headline metric)
  ``watermark_lag``        max event-time lag of the latest batch behind
                           the frontier (host pipelines)
  ``fallback_to_host``     bool: a compiled->host fallback is an SLO event
                           (default on — the perf cliff must be visible)
  ``overflow_replays``     max grow-and-replay cycles inside ``window_s``
  ``window_ticks``/``window_s``  rolling-window extents

Incident lifecycle: one incident per breach EPISODE — it opens on the
first breaching evaluation, accumulates evidence (breach count, worst
observed value, causes of breaching ticks) while the SLO stays in breach,
and closes (``resolved_ts``) when the objective recovers. Hysteresis, not
dedup: a steady violation produces exactly one incident, a flap produces
one per episode.

Health states: ``unhealthy`` while a latency/watermark/replay SLO is in
active breach, ``degraded`` when the only active condition is the latched
host-fallback (the pipeline serves, at host speed), ``ok`` otherwise. The
manager aggregates these per-pipeline states into fleet health and the
registry exports ``dbsp_tpu_slo_breaches_total{slo}`` (fleet scrapes add
the ``pipeline`` label).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from dbsp_tpu.obs.flight import FlightRecorder, dominant_cause, trace_slice
from dbsp_tpu.testing.tsan import maybe_instrument as _tsan_hook

__all__ = ["SLOConfig", "SLOWatchdog", "SLO_KEYS"]

# the closed set of objectives (also the only values the `slo` metric
# label ever takes — tools/check_metrics.py keeps label NAMES closed; this
# keeps the value set enumerable too). ``restore`` is a one-shot durability
# incident (corrupted-generation fallback / failed restore at deploy);
# ``transport`` latches a terminal input-endpoint failure (dead broker).
SLO_KEYS = ("p99_tick", "tick_abs", "watermark_lag", "fallback_to_host",
            "overflow_replays", "restore", "transport")

# SLOs whose active breach means the pipeline still serves, just degraded
_DEGRADED_ONLY = ("fallback_to_host", "transport")


class SLOConfig:
    """Parsed SLO objectives; ``None`` disables a check."""

    _FIELDS = ("p99_tick_seconds", "tick_p50_multiple", "watermark_lag",
               "fallback_to_host", "overflow_replays", "window_ticks",
               "window_s")

    def __init__(self, p99_tick_seconds: Optional[float] = None,
                 tick_p50_multiple: Optional[float] = None,
                 watermark_lag: Optional[float] = None,
                 fallback_to_host: bool = True,
                 overflow_replays: Optional[int] = None,
                 window_ticks: int = 256, window_s: float = 300.0):
        self.p99_tick_seconds = p99_tick_seconds
        self.tick_p50_multiple = tick_p50_multiple
        self.watermark_lag = watermark_lag
        self.fallback_to_host = bool(fallback_to_host)
        self.overflow_replays = overflow_replays
        self.window_ticks = int(window_ticks)
        self.window_s = float(window_s)
        _tsan_hook(self)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "SLOConfig":
        d = dict(d or {})
        unknown = set(d) - set(cls._FIELDS)
        if unknown:
            raise ValueError(
                f"unknown slo config keys {sorted(unknown)} "
                f"(known: {list(cls._FIELDS)})")
        return cls(**d)

    @classmethod
    def from_env(cls, env=os.environ) -> "SLOConfig":
        """Env-variable form for harnesses (bench.py --slo):
        DBSP_TPU_SLO_P99_TICK_MS, DBSP_TPU_SLO_TICK_P50_MULTIPLE,
        DBSP_TPU_SLO_WATERMARK_LAG, DBSP_TPU_SLO_OVERFLOW_REPLAYS."""
        def f(name):
            v = env.get(name)
            return float(v) if v not in (None, "") else None

        p99_ms = f("DBSP_TPU_SLO_P99_TICK_MS")
        replays = f("DBSP_TPU_SLO_OVERFLOW_REPLAYS")
        return cls(
            p99_tick_seconds=p99_ms / 1e3 if p99_ms is not None else None,
            tick_p50_multiple=f("DBSP_TPU_SLO_TICK_P50_MULTIPLE"),
            watermark_lag=f("DBSP_TPU_SLO_WATERMARK_LAG"),
            overflow_replays=int(replays) if replays is not None else None)

    def enabled(self) -> Dict[str, object]:
        out = {}
        for k in self._FIELDS[:5]:
            v = getattr(self, k)
            if v is not None and v is not False:
                out[k] = v
        return out


class SLOWatchdog:
    """Consumes a flight ring incrementally; opens/updates/closes
    incidents; exports breach metrics. ``evaluate()`` is cheap enough to
    run per controller-loop pass AND per scrape (both call it)."""

    def __init__(self, flight: FlightRecorder, config: SLOConfig,
                 registry=None, pipeline: str = "",
                 max_incidents: int = 16, freeze_window: int = 128):
        self.flight = flight
        self.config = config
        self.pipeline = pipeline
        self.freeze_window = freeze_window
        self._lock = threading.Lock()
        self._seen_seq = 0
        self._ticks: Deque[dict] = deque(maxlen=config.window_ticks)
        self._replay_ts: Deque[float] = deque(maxlen=1024)
        self._wm_lag: Optional[float] = None
        self._fallback: Optional[dict] = None
        # per-endpoint latched transport failures; a recovery event
        # (transient sink blip whose retry delivered) un-latches its
        # endpoint, so only endpoints CURRENTLY broken keep the pipeline
        # degraded
        self._transport: Dict[str, dict] = {}
        self._restore_failed: Optional[dict] = None  # latched failed restore
        self._restores: List[dict] = []  # new restore events this pass
        self._active: Dict[str, dict] = {}  # slo -> open incident
        self._incidents: Deque[dict] = deque(maxlen=max_incidents)
        self._ids = 0
        self._breach_counter = None
        if registry is not None:
            self._breach_counter = registry.counter(
                "dbsp_tpu_slo_breaches_total",
                "SLO breach episodes opened, by objective (an episode "
                "counts once however long the breach lasts)",
                labels=("slo",))
            self._incidents_counter = registry.counter(
                "dbsp_tpu_obs_incidents_total",
                "Incidents captured by the SLO watchdog")
            active_g = registry.gauge(
                "dbsp_tpu_slo_active_breaches_count",
                "Objectives currently in breach (0 = meeting all SLOs)")
            dropped_c = registry.counter(
                "dbsp_tpu_obs_flight_dropped_total",
                "Flight-recorder events aged out of the bounded ring, by "
                "the evicted event's kind (source of the lost history)",
                labels=("source",))

            def export():  # scrape-time collector, runs on HTTP threads
                with self._lock:
                    n_active = len(self._active)
                active_g.set(n_active)
                for src, n in self.flight.drop_stats().items():
                    dropped_c.labels(source=src).set_total(n)

            registry.register_collector(export)
        _tsan_hook(self)

    # -- evaluation ----------------------------------------------------------
    def evaluate(self) -> List[dict]:
        """One watchdog pass: fold new flight events into the rolling
        window, check every configured objective, open/update/close
        incidents. Returns incidents OPENED by this pass."""
        with self._lock:
            return self._evaluate_locked()

    def _evaluate_locked(self) -> List[dict]:  # holds: _lock
        cfg = self.config
        new = self.flight.events(since_seq=self._seen_seq)
        if new:
            self._seen_seq = new[-1]["seq"]
        new_ticks = []
        for ev in new:
            k = ev["kind"]
            if k == "tick":
                self._ticks.append(ev)
                new_ticks.append(ev)
            elif k == "tick_cause":
                # late cause amendment for an already-seen tick
                for t in reversed(self._ticks):
                    if t.get("tick") == ev.get("tick"):
                        t.setdefault("causes", [])
                        t["causes"] = list(t["causes"]) + list(
                            ev.get("causes") or [])
                        break
            elif k == "overflow_replay":
                self._replay_ts.append(ev["ts"])
            elif k == "watermark":
                self._wm_lag = ev.get("lag")
            elif k == "fallback":
                self._fallback = ev
            elif k == "transport":
                if ev.get("recovered"):
                    self._transport.pop(ev.get("endpoint", ""), None)
                else:
                    self._transport[ev.get("endpoint", "")] = ev
            elif k == "restore":
                # one-shot durability incidents, handled below (outside
                # the episode machinery: a restore is an EVENT, not a
                # condition that can stay in breach)
                if ev.get("ok") is False:
                    self._restore_failed = ev
                if ev.get("ok") is False or ev.get("fallback_from"):
                    self._restores.append(ev)
        lats = sorted(t.get("latency_ns", 0) for t in self._ticks)
        p50 = lats[len(lats) // 2] if lats else 0
        p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))] if lats else 0
        now = time.time()
        while self._replay_ts and now - self._replay_ts[0] > cfg.window_s:
            self._replay_ts.popleft()

        checks: List[Tuple[str, bool, float, float, Optional[str]]] = []
        if cfg.p99_tick_seconds is not None and lats:
            checks.append(("p99_tick", p99 / 1e9 > cfg.p99_tick_seconds,
                           p99 / 1e9, cfg.p99_tick_seconds, None))
        if cfg.tick_p50_multiple is not None and new_ticks:
            worst = max(t.get("latency_ns", 0) for t in new_ticks)
            bound = cfg.tick_p50_multiple * p50
            checks.append(("tick_abs", worst > bound, worst / 1e9,
                           bound / 1e9, None))
        if cfg.watermark_lag is not None and self._wm_lag is not None:
            checks.append(("watermark_lag",
                           self._wm_lag > cfg.watermark_lag,
                           float(self._wm_lag), float(cfg.watermark_lag),
                           "watermark"))
        if cfg.overflow_replays is not None:
            n = len(self._replay_ts)
            checks.append(("overflow_replays", n > cfg.overflow_replays,
                           float(n), float(cfg.overflow_replays),
                           "overflow"))
        if cfg.fallback_to_host and self._fallback is not None:
            checks.append(("fallback_to_host", True, 1.0, 0.0, "fallback"))
        if self._transport or "transport" in self._active:
            # also evaluated (un-breached) while an incident is open so
            # recovery RESOLVES the episode instead of freezing it active
            checks.append(("transport", bool(self._transport),
                           float(len(self._transport)), 0.0, "transport"))

        opened: List[dict] = []
        # one-shot restore incidents: a corrupted-generation fallback or a
        # failed restore each produce EXACTLY ONE incident — opened and
        # resolved in the same pass (the triggering event cannot recur),
        # never entering the episode/active machinery
        for ev in self._restores:
            inc = self._open_incident("restore", 1.0, 0.0, "restore",
                                      [], p50)
            inc["resolved_ts"] = time.time()
            del self._active["restore"]
            for field in ("reason", "fallback_from", "tick", "generation"):
                if ev.get(field) is not None:
                    inc[field] = ev[field]
            opened.append(inc)
        self._restores = []
        breaching_ticks = [t for t in new_ticks if t.get("causes")]
        for slo, breached, observed, threshold, fixed_cause in checks:
            inc = self._active.get(slo)
            if breached and inc is None:
                inc = self._open_incident(slo, observed, threshold,
                                          fixed_cause, breaching_ticks, p50)
                opened.append(inc)
            elif breached and inc is not None:
                # latched conditions (fallback) never resolve: their
                # evidence is static, so re-freezing the window + trace on
                # every pass would be permanent per-tick overhead
                if new and slo not in _DEGRADED_ONLY:
                    self._update_incident(inc, observed, fixed_cause,
                                          breaching_ticks, p50)
            elif not breached and inc is not None:
                inc["resolved_ts"] = now
                del self._active[slo]
        return opened

    # -- incidents -----------------------------------------------------------
    def _attribute(self, inc, fixed_cause,  # holds: _lock
                   breaching_ticks: List[dict], p50: float) -> None:
        if fixed_cause is not None:
            inc["cause"], inc["causes"] = fixed_cause, {fixed_cause: 1}
            return
        causes = dict(inc.get("causes") or {})
        for t in breaching_ticks:
            for c in t.get("causes") or ():
                causes[c] = causes.get(c, 0) + 1
        if causes:
            inc["causes"] = causes
            inc["cause"] = max(causes, key=causes.get)
        else:  # nothing accumulated yet: attribute from the window
            cause, counts = dominant_cause(
                [t for t in self._ticks], p50)
            inc["cause"], inc["causes"] = cause, counts

    def _freeze(self, inc: dict) -> None:
        window = self.flight.window(self.freeze_window)
        inc["window"] = window
        inc["trace"] = trace_slice(window)

    def _open_incident(self, slo, observed, threshold,  # holds: _lock
                       fixed_cause: Optional[str],
                       breaching_ticks: List[dict], p50: float) -> dict:
        self._ids += 1
        inc = {"id": self._ids, "slo": slo, "pipeline": self.pipeline,
               "opened_ts": time.time(), "last_ts": time.time(),
               "resolved_ts": None, "breach_count": 1,
               "observed": observed, "threshold": threshold,
               "cause": "unattributed", "causes": {}}
        if slo == "fallback_to_host" and self._fallback is not None:
            inc["fallback_reason"] = self._fallback.get("reason")
        self._attribute(inc, fixed_cause, breaching_ticks, p50)
        self._freeze(inc)
        self._active[slo] = inc
        self._incidents.append(inc)
        if self._breach_counter is not None:
            self._breach_counter.labels(slo=slo).inc()
            self._incidents_counter.inc()
        return inc

    def _update_incident(self, inc: dict, observed: float,
                         fixed_cause: Optional[str],
                         breaching_ticks: List[dict], p50: float) -> None:
        inc["last_ts"] = time.time()
        inc["breach_count"] += 1
        inc["observed"] = max(inc["observed"], observed)
        self._attribute(inc, fixed_cause, breaching_ticks, p50)
        self._freeze(inc)  # episode still open: keep the freshest window

    # -- reporting -----------------------------------------------------------
    def incidents(self, with_window: bool = True) -> List[dict]:
        with self._lock:
            out = []
            for inc in self._incidents:
                d = dict(inc)
                if not with_window:
                    d.pop("window", None)
                    d.pop("trace", None)
                out.append(d)
            return out

    def status(self) -> str:
        # one consistent snapshot under the lock: the latched conditions
        # and the active set must come from the same moment, or a scrape
        # racing evaluate() can render degraded-with-no-cause
        with self._lock:
            active = set(self._active)
            latched = (self._fallback is not None or
                       bool(self._transport) or
                       self._restore_failed is not None)
        if active - set(_DEGRADED_ONLY):
            return "unhealthy"
        if active or latched:
            return "degraded"
        return "ok"

    @property
    def fallback_reason(self) -> Optional[str]:
        """The latched compiled->host fallback reason — or, failing that,
        a latched failed-restore reason — if any. DURABLE: the watchdog
        retains it after the one-shot flight event ages out of the bounded
        ring (consumers must read it here, not rescan the ring)."""
        with self._lock:
            fb = self._fallback
            rf = self._restore_failed
        if fb is not None:
            return fb.get("reason")
        if rf is not None:
            return f"restore failed: {rf.get('reason')}"
        return None

    def status_dict(self) -> dict:
        with self._lock:
            active = sorted(self._active)
            last = self._incidents[-1] if self._incidents else None
            n = len(self._incidents)
        return {
            "status": self.status(),
            "fallback_reason": self.fallback_reason,
            "active": active,
            "incidents": n,
            "last_incident": None if last is None else {
                "id": last["id"], "slo": last["slo"],
                "cause": last["cause"],
                "observed": last["observed"],
                "threshold": last["threshold"],
                "resolved": last["resolved_ts"] is not None},
            "config": self.config.enabled(),
        }
