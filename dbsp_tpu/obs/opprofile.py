"""Operator-level attribution for the COMPILED engine (EXPLAIN ANALYZE).

The host engine has had per-operator profiling since PR 1 (``profile.py``'s
``CPUProfiler`` over the scheduler-event stream — the reference's
``profile/cpu.rs`` shape); the compiled engine, the path every production
pipeline actually runs, was a black box: ONE fused XLA step program whose
tick latency we export but cannot decompose. ROOFLINE §3b attributes the
remaining kernel-side gap to "XLA step-program glue" *in aggregate*; this
module makes that attribution a per-node measurement.

Two attribution modes, one shared report schema (:data:`PROFILE_SCHEMA` —
the same rows the host profiler emits, so ``/profile`` answers one question
the same way on both engines):

* **static** — each compiled node's eval is lowered and compiled as its own
  XLA program and XLA's ``cost_analysis`` (flops / bytes accessed — the
  ROOFLINE §1 methodology) is read per node, joined with graph metadata
  (operator name, capacities, trace-ladder depth, sharding). No timing; one
  probe tick threads concrete operands through the segment chain without
  touching engine state (segments never donate).
* **measured** — :func:`measured_profile` runs N ticks with the step split
  into per-node jit segments, ``block_until_ready`` wall timing per
  segment, plus rows-in/out counters, then re-runs the SAME N ticks through
  the production fused program from the same snapshot and asserts the
  outputs and final states are bit-identical — the segmented numbers
  describe the real computation, not a divergent replica. The engine is
  rewound afterwards (snapshot/restore), so production ticks never pay for
  profiling; it runs on demand (``CompiledHandle.profile_ticks(n)``, the
  ``/profile?ticks=N`` route, ``bench.py --profile``,
  ``tools/roofline.py --per-node``) or by default when
  ``DBSP_TPU_PROFILE=segment`` is set.

Methodology caveats, stated once: segments do NOT donate their state
operands (the fused program does) and lost cross-operator fusion inflates
the absolute numbers — the report carries ``segmentation_overhead``
(segmented / fused ms per tick) so readers can see the distortion, and
relative attribution (which node dominates) is the quantity the mode
exists for. One distortion IS corrected exactly: a value a node returns
UNTOUCHED (a leveled trace's deep levels flowing through its state, a
trace view handing consumers the very level tracers it was given, a
sink echoing its input batch) is elided from the segment's program
outputs and substituted from the caller's own operands after the call —
identity, not approximation — so a node is charged for what it computes,
not for round-tripping state the fused program would alias in place
(pre-elision, the two q4 CTrace nodes' pass-through copies dominated the
whole attribution table). Sharded (``workers > 1``) circuits run the
whole step inside one ``shard_map`` and are not segmentable; profiling them
raises :class:`ProfileError` (the ``/profile`` route degrades to the static
metadata it can still serve).

Per-node metric families (``dbsp_tpu_compiled_node_seconds{node,kind}`` /
``dbsp_tpu_compiled_node_rows_total{node,kind}``) register ONLY through
:func:`export_node_metrics` — the cardinality gate ``tools/check_metrics.py``
(rule 4) pins to this module — and only after a profile actually ran, so a
pipeline that never profiles exports no per-node series. Families are
top-N capped (``DBSP_TPU_PROFILE_TOP_N``, default 16; the tail aggregates
under ``node="other"``).
"""

from __future__ import annotations

import os
import re
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "PROFILE_SCHEMA", "ProfileError", "ProfileDivergence", "check_report",
    "report_dot",
    "static_profile", "measured_profile", "graph_profile",
    "export_node_metrics", "summarize_for_bench", "env_default_ticks",
    "SegmentedStep", "dryrun",
]

PROFILE_SCHEMA = "dbsp_tpu.profile/v1"

# row keys every operator entry must carry in BOTH engine modes — the
# shared /profile contract (tests/test_opprofile.py round-trips it)
ROW_KEYS = ("node", "name", "kind", "total_ms", "evals", "share", "meta")
REPORT_KEYS = ("schema", "mode", "steps", "operators")


class ProfileError(RuntimeError):
    pass


class ProfileDivergence(ProfileError):
    """Segmented run disagreed with the fused program — a real engine bug
    (or donation hazard), never a 'profiling unsupported here' condition;
    surfaces instead of degrading to the graph report."""


def env_default_ticks() -> Optional[int]:
    """``DBSP_TPU_PROFILE=segment`` arms measured profiling by default on
    the ``/profile`` surfaces; ``DBSP_TPU_PROFILE_TICKS`` sets N."""
    if os.environ.get("DBSP_TPU_PROFILE", "") == "segment":
        return int(os.environ.get("DBSP_TPU_PROFILE_TICKS", "8"))
    return None


def check_report(report: dict) -> dict:
    """Validate the shared report schema (raises :class:`ProfileError`);
    returns the report so callers can chain."""
    missing = [k for k in REPORT_KEYS if k not in report]
    if missing:
        raise ProfileError(f"profile report missing keys {missing}")
    if report["schema"] != PROFILE_SCHEMA:
        raise ProfileError(
            f"unknown profile schema {report['schema']!r} "
            f"(expected {PROFILE_SCHEMA!r})")
    if report["mode"] not in ("host", "compiled"):
        raise ProfileError(f"unknown profile mode {report['mode']!r}")
    for row in report["operators"]:
        miss = [k for k in ROW_KEYS if k not in row]
        if miss:
            raise ProfileError(
                f"operator row {row.get('name')!r} missing keys {miss}")
    return report


# ---------------------------------------------------------------------------
# segmented execution
# ---------------------------------------------------------------------------


class _SegCtx:
    """Per-segment stand-in for ``compiler._Ctx``: one node's requirements,
    sink outputs, and window-GC bounds, captured inside that node's own
    traced program instead of the shared whole-step trace."""

    def __init__(self, feeds: Dict[int, Any], states: Dict[str, Any]):
        self.feeds = feeds
        self.states = states  # CZ1Output reads its partner's INPUT state
        self.outputs: Dict[int, Any] = {}
        self.reqs: List[jnp.ndarray] = []
        self.req_index: List[Tuple[Any, str]] = []
        self.gc_bounds: Dict[int, jnp.ndarray] = {}

    def require(self, cnode, key: str, scalar) -> None:
        self.req_index.append((cnode, key))
        self.reqs.append(jnp.asarray(scalar, jnp.int64))


def _cost_of(executable) -> Dict[str, float]:
    """XLA cost analysis of one compiled segment (flops / bytes accessed —
    the ROOFLINE §1 accounting); zeros when the backend can't answer."""
    try:
        c = executable.cost_analysis()
        if isinstance(c, (list, tuple)):
            c = c[0] if c else {}
        return {"flops": float(c.get("flops", 0.0)),
                "bytes": float(c.get("bytes accessed", 0.0))}
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return {"flops": 0.0, "bytes": 0.0}


def _live_rows(v) -> int:
    """Live-row count of one inter-node value (device reduction + fetch —
    profiling-mode only, never on the hot path)."""
    from dbsp_tpu.compiled import cnodes as cnmod
    from dbsp_tpu.zset.batch import Batch

    if v is None:
        return 0
    if isinstance(v, Batch):
        return int(jnp.sum(v.weights != 0))
    if isinstance(v, cnmod.CView):
        return int(jnp.sum(v.delta.weights != 0))
    return 0


class SegmentedStep:
    """The compiled eval sequence as per-node AOT-compiled jit segments.

    Mirrors ``CompiledHandle._run_nodes`` exactly — same eval order, same
    ``ctx`` protocol (feeds / partner states / sink outputs / window-GC
    truncation applied after the node loop) — but each node's eval is its
    own compiled program, so wall time, rows, and XLA cost analysis exist
    PER NODE. Segments never donate: the caller's state dict leaves are
    read-only inputs, which is what makes probe ticks side-effect-free.
    """

    def __init__(self, ch):
        from dbsp_tpu.compiled import cnodes as cnmod

        if ch.mesh is not None:
            raise ProfileError(
                "segmented profiling supports single-worker circuits only: "
                "a sharded step runs as one shard_map program whose "
                "collectives cannot be split per node")
        self.ch = ch
        self._cn = cnmod
        self._segments: Dict[int, Tuple[Any, Optional[str]]] = {}
        self._gc_segments: Dict[int, Any] = {}
        self._gen_exec = None
        self.costs: Dict[Any, Dict[str, float]] = {}

    # -- per-node programs --------------------------------------------------
    def _partner_key(self, cn) -> Optional[str]:
        if isinstance(cn, self._cn.CZ1Output):
            return str(cn.node.partner)
        return None

    def _segment(self, cn, args):
        # keyed on the FULL argument signature (tree structure + leaf
        # shape/dtype): a compiled executable only accepts exactly what
        # it was lowered with, and inter-node values legitimately vary
        # across ticks — feed present/absent on input nodes, sorted-run
        # aux tags and CAPACITIES downstream of an empty vs fed tick (an
        # unfed input emits its default-cap empty batch, not the feed
        # bucket's). The warmup dry pass replays the exact measured
        # sequence, so every signature compiles outside the timed walls.
        idx = cn.node.index
        leaves, treedef = jax.tree_util.tree_flatten(args)
        sig = tuple((getattr(x, "shape", ()),
                     str(getattr(x, "dtype", type(x).__name__)))
                    for x in leaves)
        key = (idx, treedef, sig)
        ent = self._segments.get(key)
        if ent is not None:
            return ent
        pkey = self._partner_key(cn)
        meta: Dict[str, Any] = {}

        def fn(state, ins, feed, partner_state):
            ctx = _SegCtx({idx: feed} if feed is not None else {},
                          {pkey: partner_state} if pkey is not None else {})
            st2, out = cn.eval(ctx, state, list(ins))
            # identity pass-through elision (module doc): any returned
            # leaf that IS one of the operand tracers (state levels
            # flowing through, views handing back their inputs, sinks
            # echoing batches) — or a repeat of an already-emitted output
            # leaf — leaves the program and is reconstructed from the
            # caller's operands after the call. Exact by construction:
            # the tracer identity proves the value is the operand.
            arg_leaves = jax.tree_util.tree_flatten(
                (state, ins, feed, partner_state))[0]
            env = {}
            for i, leaf in enumerate(arg_leaves):
                if isinstance(leaf, jax.core.Tracer):
                    env.setdefault(id(leaf), i)
            ret_leaves, ret_def = jax.tree_util.tree_flatten(
                (st2, out, dict(ctx.outputs)))
            plan: List[Tuple[str, int]] = []
            kept: List[Any] = []
            emitted: Dict[int, int] = {}
            for leaf in ret_leaves:
                lid = id(leaf)
                if isinstance(leaf, jax.core.Tracer) and lid in env:
                    plan.append(("arg", env[lid]))
                    continue
                if isinstance(leaf, jax.core.Tracer) and lid in emitted:
                    plan.append(("out", emitted[lid]))
                    continue
                if isinstance(leaf, jax.core.Tracer):
                    emitted[lid] = len(kept)
                plan.append(("out", len(kept)))
                kept.append(leaf)
            meta["plan"], meta["ret_def"] = plan, ret_def
            return tuple(kept), tuple(ctx.reqs), dict(ctx.gc_bounds)

        compiled = jax.jit(fn).lower(*args).compile()
        self.costs[idx] = _cost_of(compiled)
        plan, ret_def = meta["plan"], meta["ret_def"]

        def executable(state, ins, feed, partner_state):
            kept, reqs, gc = compiled(state, ins, feed, partner_state)
            arg_leaves = jax.tree_util.tree_flatten(
                (state, ins, feed, partner_state))[0]
            leaves = [arg_leaves[i] if kind == "arg" else kept[i]
                      for kind, i in plan]
            st2, out, outs = jax.tree_util.tree_unflatten(ret_def, leaves)
            return st2, out, reqs, gc, outs

        ent = self._segments[key] = (executable, pkey)
        return ent

    def _gc_segment(self, gidx: int, st, bound):
        ex = self._gc_segments.get(gidx)
        if ex is not None:
            return ex
        cnmod = self._cn

        def fn(st, bound):
            levels, base = st
            return (tuple(cnmod.truncate_below(lvl, bound)
                          for lvl in levels), base)

        ex = self._gc_segments[gidx] = jax.jit(fn).lower(st, bound).compile()
        return ex

    def _run_gen(self, tick):
        ch = self.ch
        targ = jnp.asarray(tick, jnp.int64)
        if self._gen_exec is None:
            def fn(t):
                raw = ch._gen_fn(t)
                return {ch._op_to_index[id(getattr(h, "_op", h))]: b
                        for h, b in raw.items()}

            self._gen_exec = jax.jit(fn).lower(targ).compile()
            self.costs["gen"] = _cost_of(self._gen_exec)
        return self._gen_exec(targ)

    # -- one tick -----------------------------------------------------------
    def run_tick(self, states: Dict[str, Any], feeds_by_idx: Dict[int, Any],
                 tick: int, rec: Optional["_Recorder"] = None,
                 spans=None, plan: Optional[list] = None,
                 plan_out: Optional[list] = None):
        """One tick of the eval sequence, node by node. Returns
        ``(new_states, outputs, refs)`` where ``refs`` carries the
        per-node (inputs, output) references the caller may count rows
        over AFTER its wall timer stopped (row counting is device work
        that must not pollute the attribution).

        ``plan_out`` (warmup) records each node's resolved executable in
        eval order; ``plan`` (measured ticks) replays that recording —
        the sequence is deterministic, so the measured loop skips the
        per-node signature computation entirely and its tick walls carry
        only dispatch + device time."""
        ch = self.ch
        values: Dict[int, Any] = {}
        new_states: Dict[str, Any] = {}
        outputs: Dict[int, Any] = {}
        gc_all: Dict[int, Any] = {}
        refs: List[Tuple[int, tuple, Any, Any]] = []
        if ch._gen_fn is not None:
            t0 = time.perf_counter_ns()
            feeds_by_idx = self._run_gen(tick)
            jax.block_until_ready(feeds_by_idx)
            if rec is not None:
                rec.note("gen", time.perf_counter_ns() - t0)
        for pos, cn in enumerate(ch.cnodes):
            idx = cn.node.index
            ins = tuple(values[i] for i in cn.node.inputs)
            st = states.get(str(idx))
            feed = feeds_by_idx.get(idx)
            pkey = self._partner_key(cn)
            pstate = states.get(pkey) if pkey is not None else None
            args = (st, ins, feed, pstate)
            if plan is not None:
                executable = plan[pos]
            else:
                executable, _ = self._segment(cn, args)
                if plan_out is not None:
                    plan_out.append(executable)
            label = f"{cn.op.name}[{idx}]"
            if spans is not None:
                spans.begin(label, cat="operator")
            t0 = time.perf_counter_ns()
            st2, out, _reqs, gc, outs = executable(*args)
            jax.block_until_ready((st2, out, outs))
            dt = time.perf_counter_ns() - t0
            if spans is not None:
                spans.end(label)
            if st2 is not None:
                new_states[str(idx)] = st2
            values[idx] = out
            outputs.update(outs)
            gc_all.update(gc)
            if rec is not None:
                rec.note(idx, dt)
                refs.append((idx, ins, out, feed))
        # window-GC truncation: mirrors the post-loop of _run_nodes; the
        # time is attributed to the truncated TRACE node (kind "gc")
        for gidx, bound in gc_all.items():
            key = str(gidx)
            st = new_states.get(key)
            if st is None:
                continue
            ex = self._gc_segment(int(gidx), st, bound)
            t0 = time.perf_counter_ns()
            st2 = ex(st, bound)
            jax.block_until_ready(st2)
            if rec is not None:
                rec.note_gc(int(gidx), time.perf_counter_ns() - t0)
            new_states[key] = st2
        return new_states, outputs, refs


class _Recorder:
    """Per-node accumulators over a measured run."""

    def __init__(self):
        self.ns: Dict[Any, int] = {}
        self.gc_ns: Dict[int, int] = {}
        self.rows_in: Dict[int, int] = {}
        self.rows_out: Dict[int, int] = {}
        self.tick_walls: List[int] = []

    def note(self, key, dt: int) -> None:
        self.ns[key] = self.ns.get(key, 0) + dt

    def note_gc(self, idx: int, dt: int) -> None:
        self.gc_ns[idx] = self.gc_ns.get(idx, 0) + dt

    def count_rows(self, refs) -> None:
        for idx, ins, out, feed in refs:
            rin = sum(_live_rows(v) for v in ins) + _live_rows(feed)
            self.rows_in[idx] = self.rows_in.get(idx, 0) + rin
            self.rows_out[idx] = self.rows_out.get(idx, 0) + _live_rows(out)


# ---------------------------------------------------------------------------
# comparison plumbing
# ---------------------------------------------------------------------------


def _np_tree(tree):
    """Materialize a device pytree to host numpy — taken BEFORE the fused
    comparison run so no compared buffer can be donated away under us."""
    return jax.device_get(tree)


def _tree_mismatches(label: str, a, b) -> List[str]:
    fa, ta = jax.tree_util.tree_flatten(a)
    fb, tb = jax.tree_util.tree_flatten(b)
    if ta != tb:
        return [f"{label}: tree structure differs ({ta} != {tb})"]
    out = []
    for i, (x, y) in enumerate(zip(fa, fb)):
        x, y = np.asarray(x), np.asarray(y)
        if x.shape != y.shape or x.dtype != y.dtype:
            out.append(f"{label}: leaf {i} shape/dtype differs "
                       f"({x.shape}/{x.dtype} != {y.shape}/{y.dtype})")
            continue
        eq = (np.array_equal(x, y, equal_nan=True) if x.dtype.kind == "f"
              else np.array_equal(x, y))
        if not eq:
            out.append(f"{label}: leaf {i} differs")
    return out


def _save_handle_counters(ch) -> dict:
    """The handle bookkeeping a profile run must not leak into: latency
    samples, cause annotations, the requirement running-max, and the
    outputs dict production readers poll."""
    return {"req": ch._req,
            "lat": len(ch.step_times_ns),
            "causes": len(ch.tick_causes),
            "pending": set(ch._pending_causes),
            "outputs": ch.last_outputs,
            "overhead": {k: len(v) for k, v in ch.host_overhead_ns.items()}}


def _restore_handle_counters(ch, saved: dict) -> None:
    ch._req = saved["req"]
    del ch.step_times_ns[saved["lat"]:]
    del ch.tick_causes[saved["causes"]:]
    ch._pending_causes = set(saved["pending"])
    ch.last_outputs = saved["outputs"]
    for k, v in ch.host_overhead_ns.items():
        del v[saved["overhead"].get(k, 0):]


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------


def _node_rows(ch, seg: SegmentedStep, rec: Optional[_Recorder],
               wall_ns: int, evals: int) -> List[dict]:
    rows = []
    for cn in ch.cnodes:
        idx = cn.node.index
        ns = 0
        if rec is not None:
            ns = rec.ns.get(idx, 0) + rec.gc_ns.get(idx, 0)
        row = {"node": idx, "name": cn.op.name,
               "kind": type(cn).__name__,
               "total_ms": round(ns / 1e6, 3), "evals": evals,
               "share": round(ns / max(wall_ns, 1), 4),
               "meta": cn.profile_meta()}
        if rec is not None:
            row["rows_in"] = rec.rows_in.get(idx, 0)
            row["rows_out"] = rec.rows_out.get(idx, 0)
            if idx in rec.gc_ns:
                row["gc_ms"] = round(rec.gc_ns[idx] / 1e6, 3)
        cost = seg.costs.get(idx)
        if cost is not None:
            row["flops"] = cost["flops"]
            row["bytes"] = cost["bytes"]
        rows.append(row)
    if rec is not None and "gen" in rec.ns:
        ns = rec.ns["gen"]
        rows.append({"node": -1, "name": "generate", "kind": "Generator",
                     "total_ms": round(ns / 1e6, 3), "evals": evals,
                     "share": round(ns / max(wall_ns, 1), 4),
                     "rows_in": 0, "rows_out": 0,
                     "meta": {"caps": {}, "inputs": [], "sharded": False},
                     **(seg.costs.get("gen") or {})})
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def static_profile(ch, feeds: Optional[dict] = None) -> dict:
    """Compile-time attribution: per-node XLA ``cost_analysis`` joined with
    graph metadata. Executes ONE probe tick over (a local view of) the
    live states purely to thread concrete operands through the segment
    chain — segments never donate, so engine state is untouched and no
    snapshot is needed. Raises :class:`ProfileError` on sharded circuits.
    """
    seg = SegmentedStep(ch)
    feeds_idx = ch._feed_indices(feeds) if feeds else {}
    states = dict(ch.states)
    seg.run_tick(states, feeds_idx, tick=0)
    rows = _node_rows(ch, seg, rec=None, wall_ns=1, evals=0)
    total_bytes = sum(r.get("bytes", 0.0) for r in rows) or 1.0
    for r in rows:
        if "bytes" in r:
            r["bytes_share"] = round(r["bytes"] / total_bytes, 4)
    rows.sort(key=lambda r: -r.get("bytes", 0.0))
    return {"schema": PROFILE_SCHEMA, "mode": "compiled", "steps": 0,
            "attribution": "static", "operators": rows, "measured": None}


def graph_profile(ch) -> dict:
    """Degraded attribution for circuits the segmented profiler cannot
    split (sharded circuits: the whole step is one ``shard_map`` program):
    graph metadata only — node names, kinds, capacities, edges — no costs,
    no timing. The ``/profile`` route serves this instead of erroring, so
    a sharded pipeline still answers with its operator inventory."""
    rows = [{"node": cn.node.index, "name": cn.op.name,
             "kind": type(cn).__name__, "total_ms": 0.0, "evals": 0,
             "share": 0.0, "meta": cn.profile_meta()} for cn in ch.cnodes]
    return {"schema": PROFILE_SCHEMA, "mode": "compiled", "steps": 0,
            "attribution": "graph", "operators": rows, "measured": None}


def measured_profile(ch, n: Optional[int] = None, t0: int = 0,
                     feeds_list: Optional[Sequence[dict]] = None,
                     spans=None, check: bool = True,
                     registry=None) -> dict:
    """Measured attribution: run ``n`` ticks segmented (per-node timing),
    re-run them through the production fused program from the same
    snapshot, assert bit-identity, and REWIND — the engine resumes exactly
    where it stood (see module doc for the full protocol).

    ``feeds_list`` supplies per-tick feeds for circuits without a
    ``gen_fn`` ({handle-or-op: Batch} dicts; capacities must be stable
    across the ticks — the engine's bucketed feed caps already are).
    ``registry`` exports the gated per-node metric families from the
    result. ``check=False`` reports mismatches instead of raising."""
    n = int(n or env_default_ticks() or 8)
    if ch.mesh is not None:
        raise ProfileError(
            "segmented profiling supports single-worker circuits only")
    if ch._gen_fn is None and feeds_list is None:
        feeds_list = [{} for _ in range(n)]
    if feeds_list is not None:
        feeds_list = list(feeds_list)[:n]
        feeds_list += [{}] * (n - len(feeds_list))
    # per-tick cost is delta-proportional, so attribution over EMPTY
    # ticks describes fixed per-node overhead, not a workload — flagged
    # in the report so readers (and the /profile route on an idle served
    # pipeline) can tell the two apart
    idle_inputs = ch._gen_fn is None and all(not f for f in feeds_list)

    # canonical start point: snapshot, then restore — both runs read the
    # POST-restore (repadded) state, so their input bits are identical
    snap = ch.snapshot()
    saved = _save_handle_counters(ch)
    ch.restore(snap)
    start = ch.states

    seg = SegmentedStep(ch)
    rec = _Recorder()

    def tick_feeds(i):
        if feeds_list is None:
            return {}
        return ch._feed_indices(feeds_list[i]) if feeds_list[i] else {}

    # warmup: one full DRY PASS of the exact measured sequence on a
    # throwaway state view — every segment (and the gen program) compiles
    # HERE, outside the measured walls. A single tick-0 pass is not
    # enough: segments are keyed on argument signature, which varies with
    # each tick's feed pattern AND with upstream emptiness (run-tag aux,
    # empty-vs-fed capacities), so only replaying the real sequence
    # (states threaded, same feeds) covers every signature the measured
    # loop will hit. The pass also RECORDS each tick's executable plan,
    # so the measured loop skips signature computation entirely (at mini
    # scales that per-node host work measurably diluted attribution).
    warm_states = dict(start)
    plans: List[list] = []
    for i in range(n):
        plan_i: list = []
        warm_states, _, _ = seg.run_tick(warm_states, tick_feeds(i),
                                         t0 + i, plan_out=plan_i)
        plans.append(plan_i)

    states = dict(start)
    seg_out_np = []
    for i in range(n):
        feeds_idx = tick_feeds(i)
        if spans is not None:
            # tick -> operator nesting in the /trace window (and one
            # TOP-LEVEL span per tick, so the recorder's bounded step ring
            # evicts whole ticks, not individual operator slices)
            spans.begin(f"profile_tick[{t0 + i}]", cat="step")
        w0 = time.perf_counter_ns()
        states, outputs, refs = seg.run_tick(states, feeds_idx, t0 + i,
                                             rec=rec, spans=spans,
                                             plan=plans[i])
        rec.tick_walls.append(time.perf_counter_ns() - w0)
        if spans is not None:
            spans.end(f"profile_tick[{t0 + i}]")
        rec.count_rows(refs)  # device reductions — outside the wall
        seg_out_np.append(_np_tree(outputs))
    seg_final_np = _np_tree(states)

    # fused comparison run: the production step program, same start bits
    # (ch.states is still `start`; the first fused step donates it, which
    # is why the segmented results were materialized to numpy above)
    fused_ns = []
    fused_out_np = []
    for i in range(n):
        f = feeds_list[i] if feeds_list is not None else None
        w0 = time.perf_counter_ns()
        ch.step(tick=t0 + i, feeds=f, block=True)
        fused_ns.append(time.perf_counter_ns() - w0)
        fused_out_np.append(_np_tree(ch.last_outputs))
    fused_final_np = _np_tree(ch.states)

    mism: List[str] = []
    for i in range(n):
        mism += _tree_mismatches(f"tick[{t0 + i}].outputs",
                                 seg_out_np[i], fused_out_np[i])
    mism += _tree_mismatches("final_states", seg_final_np, fused_final_np)

    # rewind: the profiled ticks were hypothetical — production resumes
    # from the pre-profile snapshot with its counters intact
    ch.restore(snap)
    _restore_handle_counters(ch, saved)

    if check and mism:
        raise ProfileDivergence(
            f"segmented step diverged from the fused program "
            f"({len(mism)} mismatches): {mism[:4]}")

    wall_ns = sum(rec.tick_walls)
    node_ns = (sum(v for k, v in rec.ns.items()) +
               sum(rec.gc_ns.values()))
    fused_sorted = sorted(fused_ns)
    fused_med = fused_sorted[len(fused_sorted) // 2]
    seg_ms = wall_ns / n / 1e6
    fused_ms = fused_med / 1e6
    rows = _node_rows(ch, seg, rec, wall_ns, evals=n)
    report = {
        "schema": PROFILE_SCHEMA, "mode": "compiled", "steps": n,
        "t0": t0, "attribution": "measured", "operators": rows,
        "measured": {
            "ticks": n,
            "idle_inputs": idle_inputs,
            "segmented_ms_per_tick": round(seg_ms, 3),
            "fused_ms_per_tick": round(fused_ms, 3),
            "segmentation_overhead": round(seg_ms / max(fused_ms, 1e-9), 3),
            "attributed_fraction": round(node_ns / max(wall_ns, 1), 4),
            "bit_identical": not mism,
            "mismatches": mism[:8],
        },
    }
    if registry is not None:
        export_node_metrics(registry, report)
    return report


# ---------------------------------------------------------------------------
# surfaces: metrics gate, graphviz, bench summary
# ---------------------------------------------------------------------------


def export_node_metrics(registry, report: dict,
                        top_n: Optional[int] = None) -> None:
    """The ONLY registration site for the per-node metric families — the
    cardinality gate ``tools/check_metrics.py`` rule 4 enforces. Gated
    twice: the families do not exist until a MEASURED profile ran (a
    pipeline that never profiles exports no per-node series), and only the
    top-N nodes by time get their own label children (``node="other"``
    aggregates the tail), bounding series count regardless of circuit
    size."""
    top_n = top_n if top_n is not None else int(
        os.environ.get("DBSP_TPU_PROFILE_TOP_N", "16"))
    ops = [r for r in report.get("operators", ()) if r.get("total_ms")]
    if not ops:
        return
    sec = registry.gauge(
        "dbsp_tpu_compiled_node_seconds",
        "Per-node seconds over the last segmented profile run "
        "(obs/opprofile.py; top-N nodes, tail aggregates as node=other)",
        labels=("node", "kind"))
    rows_total = registry.counter(
        "dbsp_tpu_compiled_node_rows_total",
        "Output rows attributed per node across segmented profile runs "
        "(top-N capped like _node_seconds)",
        labels=("node", "kind"))
    # the gauge family is "the LAST profile run": drop the previous run's
    # children or nodes that fell out of this run's top-N would keep
    # serving stale seconds next to the fresh series (the counter is
    # cumulative across runs by contract and must NOT be cleared)
    sec.clear_children()
    other_s, other_r = 0.0, 0
    for i, r in enumerate(sorted(ops, key=lambda r: -r["total_ms"])):
        if i < top_n:
            sec.labels(node=str(r["node"]), kind=r["kind"]).set(
                r["total_ms"] / 1e3)
            rows_total.labels(node=str(r["node"]), kind=r["kind"]).inc(
                r.get("rows_out", 0))
        else:
            other_s += r["total_ms"] / 1e3
            other_r += r.get("rows_out", 0)
    if other_s or other_r:
        sec.labels(node="other", kind="other").set(other_s)
        rows_total.labels(node="other", kind="other").inc(other_r)


def report_dot(report: dict) -> str:
    """Graphviz rendering of a profile report (the reference's
    ``dump_profile`` .dot shape): nodes shaded by time share, edges from
    the rows' graph metadata."""
    rows = report.get("operators", [])
    total = sum(r.get("total_ms", 0.0) for r in rows) or 1.0

    def nid(n):
        return "n" + re.sub(r"[^0-9A-Za-z]+", "_", str(n))

    lines = ["digraph profile {", '  rankdir="LR";']
    present = {str(r["node"]) for r in rows}
    for r in rows:
        ms = r.get("total_ms", 0.0)
        pct = 100.0 * ms / total
        label = f"{r['name']}\\n{ms:.1f}ms ({pct:.0f}%)"
        if not ms and r.get("bytes"):
            label = f"{r['name']}\\n{r['bytes'] / 1e6:.2f}MB"
        shade = min(9, 1 + int(pct / 12))
        lines.append(f'  {nid(r["node"])} [label="{label}", style=filled, '
                     f'colorscheme=reds9, fillcolor={shade}];')
    for r in rows:
        for i in (r.get("meta") or {}).get("inputs", ()):
            if str(i) in present:
                lines.append(f"  {nid(i)} -> {nid(r['node'])};")
    lines.append("}")
    return "\n".join(lines)


def summarize_for_bench(report: dict, top: int = 8) -> dict:
    """The compact embedding ``bench.py --profile`` puts in its JSON."""
    m = report.get("measured") or {}
    return {
        "attributed_fraction": m.get("attributed_fraction"),
        "bit_identical": m.get("bit_identical"),
        "segmented_ms_per_tick": m.get("segmented_ms_per_tick"),
        "fused_ms_per_tick": m.get("fused_ms_per_tick"),
        "segmentation_overhead": m.get("segmentation_overhead"),
        "top_operators": [
            {k: r.get(k) for k in ("node", "name", "kind", "total_ms",
                                   "share", "rows_out")}
            for r in report.get("operators", [])[:top]],
    }


# ---------------------------------------------------------------------------
# lint dryrun
# ---------------------------------------------------------------------------


def dryrun(query: str = "q4", ticks: int = 2, events_per_tick: int = 400,
           warm: int = 2) -> dict:
    """Build a mini compiled Nexmark circuit and run one measured profile
    end to end — the ``tools/lint_all.py`` front that keeps the profiler
    from silently rotting. Raises on schema drift, segmented/fused
    divergence, or attribution below 90%."""
    jax.config.update("jax_platforms", "cpu")
    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.compiled import compile_circuit
    from dbsp_tpu.nexmark import (GeneratorConfig, build_inputs, device_gen,
                                  queries)

    cfg = GeneratorConfig(seed=1)
    ept = max(events_per_tick // 50, 1)
    q = getattr(queries, query)

    def build(c):
        streams, handles = build_inputs(c)
        return handles, q(*streams).output()

    handle, (handles, _out) = Runtime.init_circuit(1, build)
    hp, ha, hb = handles

    def gen_fn(tick):
        p, a, b = device_gen.generate_tick(cfg, tick * ept, ept)
        return {hp: p, ha: a, hb: b}

    ch = compile_circuit(handle, gen_fn=gen_fn)
    ch.run_ticks(0, warm, validate_every=1)
    report = measured_profile(ch, n=ticks, t0=warm)
    check_report(report)
    m = report["measured"]
    if not m["bit_identical"]:
        raise ProfileError(
            f"{query}: segmented != fused: {m['mismatches']}")
    # attribution floor: real rot (a compile or fetch landing inside a
    # tick wall un-attributed) collapses this far below the floor; 0.85
    # leaves headroom for host-noise on tiny mini-protocol segments (the
    # committed PROFILE_q4.json artifact is separately gated >= 0.90 by
    # tests/test_opprofile.py)
    if m["attributed_fraction"] < 0.85:
        raise ProfileError(
            f"{query}: only {m['attributed_fraction']:.0%} of segmented "
            "tick time attributed to named nodes (floor: 85%)")
    return report
