"""Row-level lineage ("EXPLAIN WHY"): backward provenance slicing.

Answers the question every operator of an IVM system eventually asks:
**"why is this row in my view?"** — which input rows, through which
operators, with what Z-set weights, produced a given output row. Z-set
weights are a provenance-polynomial evaluation under the counting
semiring (Green, Karvounarakis & Tannen, *Provenance Semirings*,
PODS'07), and DBSP's integrated traces already hold the state a backward
query needs (Budiu et al., *DBSP*, VLDB'23) — so a lineage query walks
the circuit graph backward from the output node and, at each operator,
computes the *support* of the target rows against integrated state:

* **join** — probes both sides' integrated traces on the probed key
  prefix (honoring the operator's partial-key ``nk``), re-evaluates the
  join function on the matched pairs, and keeps the pairs that produce a
  target row;
* **aggregate / distinct / topk / rolling** — enumerates the target
  groups' member rows (with weights) from the input integral;
* **filter / map / flat_map** — computes the preimage by re-evaluating
  the operator's own columnar transform on candidate rows;
* **linear ops** (plus/minus/neg/sum, delay-free integrate sugar,
  shard/unshard/exchange, trace, window) — pass through.

Cost scales with the *integrated state* along the lineage path — a join
hop hash-joins the two sides' integrals host-side (O(|L| + |R| +
matches), grouped on the probed prefix), map/flat_map hops re-evaluate
over the input integral — never with the tick history: no replay, no
bisection. The query runs under the controller's step lock, so on very
large integrals one slice stalls serving for its duration; cap state
with the usual window/GC machinery before relying on live lineage.

Two engines, one slicer: the host path reads ``Spine`` state directly
(:class:`HostState`); the compiled path decodes the leveled device
states host-side through PR 3's incremental ``CompiledHandle.snapshot()``
(:class:`CompiledState`) and runs the same slicer READ-ONLY — a lineage
query never mutates serving state (tests assert bit-identity of
subsequent outputs), and sharded circuits slice per worker key-slice
with no ``unshard()`` (state readers union the worker axis host-side;
P003-clean by construction).

Interior integrals (streams between stateful operators) are
RECONSTRUCTED forward from the nearest authoritative state — trace
spines, aggregate output spines, linear-aggregate accumulators — by
re-evaluating the pure operators host-side. Raw input-table integrals
come from (a) a trace directly on the source, or (b) the opt-in
**lineage tap** (:func:`enable_taps` / ``DBSP_TPU_LINEAGE_TAP=1`` /
pipeline-config ``lineage_taps``): a host-side spine each
``ZSetInput`` folds its drained deltas into (both engines drain inputs
through the same host handle, so one tap serves both; host checkpoints
persist it via ``state_dict``). Without either, the slice stops at the
deepest reconstructible frontier and flags the hop ``unresolved``.

Correctness oracle: :func:`provenance_oracle` is an INDEPENDENT
provenance-semiring full recompute on the host — every input row tagged
with a set-of-row-ids aux (capped at ``prov_cap`` with an explicit
``truncated`` flag), evaluated forward through the circuit — and
:func:`check_against_oracle` asserts the backward slice's input leaves
equal the oracle's provenance sets (tier-1 on q1-q8, both engines;
``tools/lint_all.py``'s ``lineage_dryrun`` front keeps it red on
divergence).

Surfaces: server ``GET /lineage?view=&key=`` (+ ``?format=dot``),
manager ``GET /pipelines/<name>/lineage``, client
``PipelineHandle.why(view, key)``, a console "Why" button, the gated
metric families ``dbsp_tpu_lineage_queries_total`` /
``dbsp_tpu_lineage_seconds`` (registered ONLY here —
``tools/check_metrics.py`` rule 5), a ``lineage`` flight event per
query, and the ``python -m dbsp_tpu.obs.lineage`` CLI.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

LINEAGE_SCHEMA = "dbsp_tpu.lineage/v1"

# per-hop row cap in the served report (full counts always reported;
# tests pass max_rows=None for uncapped oracle comparison)
DEFAULT_MAX_ROWS = 64

# provenance-set cap per output row in the oracle recompute — beyond it
# the set carries an explicit truncated flag and agreement checks become
# subset checks
ORACLE_PROV_CAP = int(os.environ.get("DBSP_TPU_LINEAGE_PROV_CAP", "65536"))

ZDict = Dict[tuple, int]


class LineageError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# lineage taps (raw input-table integrals)
# ---------------------------------------------------------------------------


def enable_taps(circuit) -> int:
    """Attach a lineage tap (host spine of everything drained) to every
    ``ZSetInput`` source of ``circuit`` that doesn't have one. Returns the
    number of taps attached. Opt-in: the tap integrates the input stream
    host-side (state grows with the netted input, like any un-GC'd
    trace), which is exactly the table a lineage query resolves to."""
    from dbsp_tpu.operators.io_handles import ZSetInput
    from dbsp_tpu.trace.spine import Spine

    n = 0
    for node in circuit.nodes:
        op = node.operator
        if isinstance(op, ZSetInput) and \
                getattr(op, "lineage_tap", None) is None:
            op.lineage_tap = Spine(op.key_dtypes, op.val_dtypes)
            n += 1
    return n


def taps_env_enabled(config: Optional[dict] = None) -> bool:
    """Deploy-time tap policy: ``DBSP_TPU_LINEAGE_TAP=1`` or the pipeline
    config key ``lineage_taps``."""
    if os.environ.get("DBSP_TPU_LINEAGE_TAP", "0") != "0":
        return True
    return bool((config or {}).get("lineage_taps"))


# ---------------------------------------------------------------------------
# engine state providers
# ---------------------------------------------------------------------------


def _zadd(into: ZDict, frm: ZDict) -> ZDict:
    for r, w in frm.items():
        nw = into.get(r, 0) + w
        if nw:
            into[r] = nw
        else:
            into.pop(r, None)
    return into


def _finalize_linear(agg, acc_z: ZDict, nk: int, out_dtypes) -> ZDict:
    """Output integral of a linear aggregate from its accumulator rows
    (key -> (acc..., count) with Z-set weights): net the accumulators per
    key (linearity), then run the aggregator's own ``finalize`` so the
    reconstruction is bit-equal to the engine's."""
    import jax.numpy as jnp

    per_key: Dict[tuple, List[int]] = {}
    for row, w in acc_z.items():
        k, vals = row[:nk], row[nk:]
        acc = per_key.setdefault(k, [0] * len(vals))
        for i, v in enumerate(vals):
            acc[i] += int(v) * w
    out: ZDict = {}
    for k, acc in per_key.items():
        cnt = acc[-1]
        if cnt <= 0:
            continue
        fin = agg.finalize(
            tuple(jnp.asarray([a], jnp.int64) for a in acc[:-1]),
            jnp.asarray([cnt], jnp.int64))
        row = k + tuple(int(np.asarray(c.astype(d))[0])
                        for c, d in zip(fin, out_dtypes))
        out[row] = out.get(row, 0) + 1
    return out


class HostState:
    """Read-only integral access for the host engine: Spines directly."""

    engine = "host"

    def __init__(self, circuit):
        self.circuit = circuit

    def _op(self, idx):
        return self.circuit.nodes[idx].operator

    def trace_integral(self, idx: int) -> Optional[ZDict]:
        from dbsp_tpu.operators.trace_op import TraceOp

        op = self._op(idx)
        if isinstance(op, TraceOp):
            return op.spine.to_dict()
        return None

    def out_integral(self, idx: int) -> Optional[ZDict]:
        from dbsp_tpu.operators.aggregate import AggregateOp
        from dbsp_tpu.operators.aggregate_linear import LinearAggregateOp
        from dbsp_tpu.operators.topk import TopKOp
        from dbsp_tpu.operators.upsert import UpsertInput
        from dbsp_tpu.timeseries.rolling import RollingAggregateOp

        op = self._op(idx)
        if isinstance(op, (AggregateOp, TopKOp, RollingAggregateOp)):
            return op.out_spine.to_dict()
        if isinstance(op, LinearAggregateOp):
            return _finalize_linear(op.agg, op.acc_spine.to_dict(),
                                    len(op.key_dtypes), op.agg.out_dtypes)
        if isinstance(op, UpsertInput):
            return op.spine.to_dict()
        return None

    def source_integral(self, idx: int) -> Optional[ZDict]:
        from dbsp_tpu.operators.trace_op import TraceOp
        from dbsp_tpu.operators.upsert import UpsertInput

        op = self._op(idx)
        if isinstance(op, UpsertInput):
            return op.spine.to_dict()
        tap = getattr(op, "lineage_tap", None)
        tap_z = tap.to_dict() if tap is not None else None
        if tap_z:
            return tap_z
        # tap absent — or EMPTY, which may mean "freshly re-enabled after
        # a restore that didn't carry it", not "no input yet": a trace
        # DIRECTLY on the source holds the authoritative integral (e.g.
        # q4's bids feed a join that traces them raw), so never trust an
        # empty tap over it
        for node in self.circuit.nodes:
            if isinstance(node.operator, TraceOp) and node.inputs == [idx]:
                return node.operator.spine.to_dict()
        return tap_z

    def window_bounds(self, idx: int):
        return self._op(idx).prev  # WindowOp: last applied (a1, b1) or None

    def watermark_value(self, idx: int):
        return self._op(idx)._wm  # WatermarkMonotonic: int or None


class CompiledState:
    """Read-only integral access for the compiled engine: PR 3's
    incremental ``snapshot()`` decodes the leveled device states
    host-side. The snapshot is a deep copy — subsequent serving steps
    donate the live states, never these buffers — so the slicer is
    read-only by construction. Sharded states carry a leading worker
    axis; ``Batch.to_dict`` unions the worker slices host-side (per
    worker key-slice, no unshard node, P003-clean)."""

    engine = "compiled"

    def __init__(self, target):
        from dbsp_tpu.compiled.compiler import CompiledHandle
        from dbsp_tpu.compiled.driver import CompiledCircuitDriver

        if isinstance(target, CompiledCircuitDriver):
            self.ch = target.ch
        elif isinstance(target, CompiledHandle):
            self.ch = target
        else:
            raise LineageError(
                f"not a compiled engine target: {type(target).__name__}")
        self.circuit = self.ch.circuit
        self.snap = self.ch.snapshot()

    def _state(self, idx: int):
        return self.snap.get(str(idx))

    def _cn(self, idx: int):
        return self.ch.by_index.get(idx)

    def trace_integral(self, idx: int) -> Optional[ZDict]:
        from dbsp_tpu.compiled import cnodes

        cn = self._cn(idx)
        st = self._state(idx)
        if not isinstance(cn, cnodes.CTrace) or st is None:
            return None
        levels, _base = st
        out: ZDict = {}
        for lvl in levels:
            _zadd(out, lvl.to_dict())
        return out

    def out_integral(self, idx: int) -> Optional[ZDict]:
        from dbsp_tpu.compiled import cnodes

        cn = self._cn(idx)
        st = self._state(idx)
        if st is None:
            return None
        if isinstance(cn, cnodes.CAggregate):
            return st[0].to_dict()
        if isinstance(cn, cnodes.CLinearAggregate):
            op = cn.op
            return _finalize_linear(op.agg, st.to_dict(),
                                    len(op.key_dtypes), op.agg.out_dtypes)
        if isinstance(cn, (cnodes.CTopK, cnodes.CRolling, cnodes.CUpsertIn)):
            return st.to_dict()
        return None

    def source_integral(self, idx: int) -> Optional[ZDict]:
        from dbsp_tpu.compiled import cnodes

        cn = self._cn(idx)
        if isinstance(cn, cnodes.CUpsertIn):
            return self.out_integral(idx)
        op = self.circuit.nodes[idx].operator
        tap = getattr(op, "lineage_tap", None)
        tap_z = tap.to_dict() if tap is not None else None
        if tap_z:
            return tap_z
        # see HostState.source_integral: compiled checkpoints persist
        # cnode engine states, never the host-side tap — after a restore
        # the re-enabled tap is EMPTY while the restored trace ladder is
        # authoritative, so the direct trace wins over an empty tap
        for node in self.circuit.nodes:
            if node.inputs == [idx] and \
                    isinstance(self._cn(node.index), cnodes.CTrace):
                return self.trace_integral(node.index)
        return tap_z

    @staticmethod
    def _scalar(x) -> int:
        return int(np.asarray(x).reshape(-1)[0])

    def window_bounds(self, idx: int):
        st = self._state(idx)
        if st is None:
            return None
        a0, b0, had = st
        if not bool(np.asarray(had).reshape(-1)[0]):
            return None
        return (self._scalar(a0), self._scalar(b0))

    def watermark_value(self, idx: int):
        st = self._state(idx)
        if st is None:
            return None
        wm, valid = st
        if not bool(np.asarray(valid).reshape(-1)[0]):
            return None
        return self._scalar(wm)


def state_for(handle_or_driver):
    """The matching state provider for a stepping handle/driver."""
    from dbsp_tpu.compiled.compiler import CompiledHandle
    from dbsp_tpu.compiled.driver import CompiledCircuitDriver

    if isinstance(handle_or_driver, (CompiledCircuitDriver, CompiledHandle)):
        return CompiledState(handle_or_driver)
    return HostState(handle_or_driver.circuit)


# ---------------------------------------------------------------------------
# forward evaluation (reconstruction + provenance oracle)
# ---------------------------------------------------------------------------


class _Scalar:
    """A non-batch (control) stream value: watermark / window bounds."""

    def __init__(self, value):
        self.value = value


class _Unsupported(LineageError):
    pass


def _cols_of(z: ZDict, dtypes):
    """Column arrays (jnp, schema dtypes) + weights + the row list of a
    host z-set — the bridge back into the operators' own columnar
    transforms so forward reconstruction reuses the engine's exact fns."""
    import jax.numpy as jnp

    rows = list(z.keys())
    ws = np.asarray([z[r] for r in rows], np.int64)
    cols = tuple(
        jnp.asarray(np.asarray([r[i] for r in rows]), d)
        if rows else jnp.zeros((0,), d)
        for i, d in enumerate(dtypes))
    return rows, cols, ws


def _pyval(x):
    x = np.asarray(x)
    if x.dtype.kind in "iub":
        return int(x)
    return float(x)


def _rows_from_cols(cols, n: int) -> List[tuple]:
    mats = [np.asarray(c) for c in cols]
    return [tuple(_pyval(m[i]) for m in mats) for i in range(n)]


class _Prov:
    """Per-row provenance accumulator: id set + truncated flag."""

    __slots__ = ("ids", "truncated")

    def __init__(self, ids=(), truncated=False):
        self.ids = frozenset(ids)
        self.truncated = truncated

    def union(self, other: "_Prov", cap: int) -> "_Prov":
        ids = self.ids | other.ids
        tr = self.truncated or other.truncated
        if len(ids) > cap:
            ids = frozenset(sorted(ids)[:cap])
            tr = True
        p = _Prov()
        p.ids, p.truncated = ids, tr
        return p


def _punion(pm: Dict[tuple, _Prov], row: tuple, prov: _Prov, cap: int):
    cur = pm.get(row)
    pm[row] = prov if cur is None else cur.union(prov, cap)


class Evaluator:
    """Forward integral evaluation over the host circuit graph.

    Two modes sharing one set of per-operator forward rules:

    * **reconstruct** (``prov=False``, ``state`` given): integrals for
      the backward slicer. Stateful nodes short-circuit to authoritative
      engine state (trace spines, output spines, accumulators); only the
      pure interior ops re-evaluate.
    * **oracle** (``prov=True``, ``sources`` given): the provenance-
      semiring full recompute — everything evaluates forward from the
      input history, each row carrying the set of (source node, row) ids
      that produced it (capped at ``prov_cap`` + truncated flag).
    """

    def __init__(self, circuit, state=None, sources: Optional[Dict] = None,
                 prov: bool = False, prov_cap: int = ORACLE_PROV_CAP):
        self.circuit = circuit
        self.state = state
        self.sources = sources or {}
        self.prov = prov
        self.prov_cap = prov_cap
        self._memo: Dict[int, Any] = {}

    # -- public -------------------------------------------------------------
    def integral(self, idx: int):
        """The node's integrated value: a ZDict (batch streams) or a
        ``_Scalar`` (watermark/bounds). Raises :class:`_Unsupported` for
        operators with no forward rule; ``None`` when the value is
        unknowable (an untapped, untraced source)."""
        if idx in self._memo:
            v = self._memo[idx]
            if isinstance(v, _Unsupported):
                raise v
            return v
        try:
            v = self._eval(idx)
        except _Unsupported as e:
            self._memo[idx] = e
            raise
        self._memo[idx] = v
        return v

    def prov_of(self, idx: int) -> Dict[tuple, _Prov]:
        assert self.prov
        self.integral(idx)
        return self._provs.setdefault(idx, {})

    # -- internals ----------------------------------------------------------
    @property
    def _provs(self) -> Dict[int, Dict[tuple, _Prov]]:
        if not hasattr(self, "_provs_"):
            self._provs_: Dict[int, Dict[tuple, _Prov]] = {}
        return self._provs_

    def _in_schema(self, idx: int):
        schema = self.circuit.nodes[idx].schema
        if schema is None:
            raise _Unsupported(f"node {idx} has no schema metadata")
        return (*schema[0], *schema[1])

    def _eval(self, idx: int):
        from dbsp_tpu.operators.aggregate import AggregateOp
        from dbsp_tpu.operators.aggregate_linear import LinearAggregateOp
        from dbsp_tpu.operators.basic import (Apply, Minus, Neg, Plus,
                                              SumN)
        from dbsp_tpu.operators.distinct import DistinctOp
        from dbsp_tpu.operators.filter_map import FilterOp, FlatMapOp, MapOp
        from dbsp_tpu.operators.io_handles import (OutputOperator,
                                                   ZSetInput)
        from dbsp_tpu.operators.join import JoinOp
        from dbsp_tpu.operators.shard_op import ExchangeOp, UnshardOp
        from dbsp_tpu.operators.topk import TopKOp
        from dbsp_tpu.operators.trace_op import TraceOp
        from dbsp_tpu.operators.upsert import UpsertInput
        from dbsp_tpu.operators.z1 import Z1, _PlusNamed
        from dbsp_tpu.timeseries.rolling import RollingAggregateOp
        from dbsp_tpu.timeseries.watermark import WatermarkMonotonic
        from dbsp_tpu.timeseries.window import WindowOp

        node = self.circuit.nodes[idx]
        op = node.operator
        ins = node.inputs

        if isinstance(op, (ZSetInput, UpsertInput)):
            return self._eval_source(idx, op)
        if isinstance(op, TraceOp):
            if not self.prov and self.state is not None:
                z = self.state.trace_integral(idx)
                if z is not None:
                    return z
            return self._pass(idx, ins[0])
        if isinstance(op, OutputOperator):
            return self._pass(idx, ins[0])
        if isinstance(op, (ExchangeOp, UnshardOp)):
            return self._pass(idx, ins[0])
        if isinstance(op, _PlusNamed):
            # integrate sugar (acc = s + z1(acc)): the *z-set* integral of
            # the accumulator stream IS the integral of the delta input —
            # follow the non-feedback edge
            src = self._nonstrict_input(node)
            return self._pass(idx, src)
        if isinstance(op, Z1):
            raise _Unsupported("z^-1 (delay) has no integral-level "
                               "lineage rule")
        if isinstance(op, WatermarkMonotonic):
            return self._eval_watermark(idx, op, ins[0])
        if isinstance(op, Apply):
            v = self.integral(ins[0])
            if isinstance(v, _Scalar):
                return _Scalar(op.fn(v.value))
            raise _Unsupported(f"apply({op.name}) over batch streams")
        if isinstance(op, WindowOp):
            return self._eval_window(idx, op, ins)
        if isinstance(op, FilterOp):
            return self._eval_filter(idx, op, ins[0])
        if isinstance(op, MapOp):
            return self._eval_map(idx, op, ins[0])
        if isinstance(op, FlatMapOp):
            return self._eval_flat_map(idx, op, ins[0])
        if isinstance(op, JoinOp):
            return self._eval_join(idx, op, ins)
        if isinstance(op, (Plus, Minus, SumN, Neg)):
            return self._eval_linear(idx, op, ins)
        if isinstance(op, DistinctOp):
            return self._eval_distinct(idx, ins[0])
        if isinstance(op, AggregateOp):
            if not self.prov and self.state is not None:
                z = self.state.out_integral(idx)
                if z is not None:
                    return z
            return self._eval_aggregate(idx, op.agg, len(op.key_dtypes),
                                        op.agg.out_dtypes, ins[0])
        if isinstance(op, LinearAggregateOp):
            if not self.prov and self.state is not None:
                z = self.state.out_integral(idx)
                if z is not None:
                    return z
            return self._eval_linear_aggregate(idx, op, ins[0])
        if isinstance(op, TopKOp):
            if not self.prov and self.state is not None:
                z = self.state.out_integral(idx)
                if z is not None:
                    return z
            return self._eval_topk(idx, op, ins[0])
        if isinstance(op, RollingAggregateOp):
            if not self.prov and self.state is not None:
                z = self.state.out_integral(idx)
                if z is not None:
                    return z
            return self._eval_rolling(idx, op, ins[0])
        raise _Unsupported(f"operator {op.name!r} "
                           f"({type(op).__name__}) has no lineage rule")

    def _nonstrict_input(self, node) -> int:
        for i in node.inputs:
            if self.circuit.nodes[i].kind != "strict_output":
                return i
        raise _Unsupported("feedback-only operator")

    # -- per-op forward rules ------------------------------------------------
    def _eval_source(self, idx, op):
        from dbsp_tpu.operators.upsert import UpsertInput

        z = self.sources.get(idx)
        if z is None and self.state is not None:
            z = self.state.source_integral(idx)
        if z is None and isinstance(op, UpsertInput):
            z = op.spine.to_dict()
        if z is None:
            return None
        if self.prov:
            pm = self._provs.setdefault(idx, {})
            for r in z:
                pm[r] = _Prov([(idx, r)])
        return dict(z)

    def _pass(self, idx, src):
        v = self.integral(src)
        if self.prov and isinstance(v, dict):
            self._provs[idx] = dict(self._provs.setdefault(src, {}))
        return None if v is None else (dict(v) if isinstance(v, dict) else v)

    def _eval_watermark(self, idx, op, src):
        if not self.prov and self.state is not None:
            return _Scalar(self.state.watermark_value(idx))
        z = self.integral(src)
        if z is None:
            raise _Unsupported("watermark over unknown input integral")
        if not z:
            return _Scalar(None)
        sch = self._in_schema(src)
        rows, cols, ws = _cols_of(z, sch)
        nk = len(self.circuit.nodes[src].schema[0])
        ts = np.asarray(op.ts_fn(cols[:nk], cols[nk:]))
        live = ws != 0
        if not live.any():
            return _Scalar(None)
        return _Scalar(int(ts[live].max()) - op.lateness)

    def _eval_window(self, idx, op, ins):
        trace_idx, bounds_idx = ins
        z = self.integral(trace_idx)
        if z is None:
            return None
        if not self.prov and self.state is not None:
            bounds = self.state.window_bounds(idx)
        else:
            bv = self.integral(bounds_idx)
            bounds = bv.value if isinstance(bv, _Scalar) else None
        if bounds is None:
            out: ZDict = {}
        else:
            a, b = bounds
            out = {r: w for r, w in z.items() if a <= r[0] < b}
        if self.prov:
            src_pm = self._provs.setdefault(trace_idx, {})
            self._provs[idx] = {r: src_pm[r] for r in out if r in src_pm}
        return out

    def _eval_filter(self, idx, op, src):
        z = self.integral(src)
        if z is None:
            return None
        sch = self._in_schema(src)
        nk = len(self.circuit.nodes[src].schema[0])
        rows, cols, ws = _cols_of(z, sch)
        keep = np.asarray(op.pred(cols[:nk], cols[nk:]))
        out = {r: z[r] for r, k in zip(rows, keep) if k}
        if self.prov:
            src_pm = self._provs.setdefault(src, {})
            self._provs[idx] = {r: src_pm[r] for r in out if r in src_pm}
        return out

    def _map_images(self, op, src) -> Optional[List[Tuple[tuple, tuple]]]:
        """(input row, image row) pairs of a MapOp over the input
        integral — shared by forward evaluation and the backward
        preimage."""
        z = self.integral(src)
        if z is None:
            return None
        sch = self._in_schema(src)
        nk = len(self.circuit.nodes[src].schema[0])
        rows, cols, _ws = _cols_of(z, sch)
        if not rows:
            return []
        nkc, nvc = op.fn(cols[:nk], cols[nk:])
        nkc, nvc = tuple(nkc), tuple(nvc)
        if op.out_schema is not None:
            kd, vd = op.out_schema
            nkc = tuple(c.astype(d) for c, d in zip(nkc, kd))
            nvc = tuple(c.astype(d) for c, d in zip(nvc, vd))
        images = _rows_from_cols((*nkc, *nvc), len(rows))
        return list(zip(rows, images))

    def _eval_map(self, idx, op, src):
        pairs = self._map_images(op, src)
        if pairs is None:
            return None
        z = self.integral(src)
        out: ZDict = {}
        pm: Dict[tuple, _Prov] = {}
        src_pm = self._provs.setdefault(src, {}) if self.prov else None
        for r, img in pairs:
            w = z[r]
            nw = out.get(img, 0) + w
            if nw:
                out[img] = nw
            else:
                out.pop(img, None)
            if src_pm is not None and r in src_pm:
                _punion(pm, img, src_pm[r], self.prov_cap)
        if self.prov:
            self._provs[idx] = {r: p for r, p in pm.items() if r in out}
        return out

    def _flat_map_images(self, op, src):
        """(input row, [image rows]) of a FlatMapOp over the integral."""
        z = self.integral(src)
        if z is None:
            return None
        sch = self._in_schema(src)
        nk = len(self.circuit.nodes[src].schema[0])
        rows, cols, _ws = _cols_of(z, sch)
        if not rows:
            return []
        nkc, nvc, keep = op.fn(cols[:nk], cols[nk:])
        nkc, nvc = tuple(nkc), tuple(nvc)
        if op.out_schema is not None:
            kd, vd = op.out_schema
            nkc = tuple(c.astype(d) for c, d in zip(nkc, kd))
            nvc = tuple(c.astype(d) for c, d in zip(nvc, vd))
        keep = np.asarray(keep)
        mats = [np.asarray(c) for c in (*nkc, *nvc)]
        out = []
        for i, r in enumerate(rows):
            imgs = [tuple(_pyval(m[f, i]) for m in mats)
                    for f in range(op.fanout) if keep[f, i]]
            out.append((r, imgs))
        return out

    def _eval_flat_map(self, idx, op, src):
        pairs = self._flat_map_images(op, src)
        if pairs is None:
            return None
        z = self.integral(src)
        out: ZDict = {}
        pm: Dict[tuple, _Prov] = {}
        src_pm = self._provs.setdefault(src, {}) if self.prov else None
        for r, imgs in pairs:
            w = z[r]
            for img in imgs:
                nw = out.get(img, 0) + w
                if nw:
                    out[img] = nw
                else:
                    out.pop(img, None)
                if src_pm is not None and r in src_pm:
                    _punion(pm, img, src_pm[r], self.prov_cap)
        if self.prov:
            self._provs[idx] = {r: p for r, p in pm.items() if r in out}
        return out

    def _join_pairs(self, op, lidx, ridx):
        """Matched (l_row, r_row, out_row, w) quadruples of the full join
        of the two integrated sides: probe on the operator's nk-column
        key prefix (partial-key joins probe exactly the prefix the engine
        probes), evaluate the join fn vectorized over the matched pairs."""
        IL, IR = self.integral(lidx), self.integral(ridx)
        if IL is None or IR is None:
            return None
        nk = op.nk
        groups: Dict[tuple, List[tuple]] = {}
        for r in IR:
            groups.setdefault(r[:nk], []).append(r)
        lrows, rrows = [], []
        for lr in IL:
            for rr in groups.get(lr[:nk], ()):
                lrows.append(lr)
                rrows.append(rr)
        if not lrows:
            return []
        import jax.numpy as jnp

        lsch = self._in_schema(lidx)
        rsch = self._in_schema(ridx)
        kcols = tuple(jnp.asarray(np.asarray([lr[i] for lr in lrows]),
                                  lsch[i]) for i in range(nk))
        lvals = tuple(jnp.asarray(np.asarray([lr[i] for lr in lrows]),
                                  lsch[i])
                      for i in range(len(self.circuit.nodes[lidx]
                                         .schema[0]),
                                     len(lsch)))
        rvals = tuple(jnp.asarray(np.asarray([rr[i] for rr in rrows]),
                                  rsch[i])
                      for i in range(len(self.circuit.nodes[ridx]
                                         .schema[0]),
                                     len(rsch)))
        ok, ov = op._left_core.fn(kcols, lvals, rvals)
        outs = _rows_from_cols((*tuple(ok), *tuple(ov)), len(lrows))
        return [(lr, rr, orow, IL[lr] * IR[rr])
                for lr, rr, orow in zip(lrows, rrows, outs)]

    def _eval_join(self, idx, op, ins):
        pairs = self._join_pairs(op, ins[0], ins[1])
        if pairs is None:
            return None
        out: ZDict = {}
        pm: Dict[tuple, _Prov] = {}
        lpm = self._provs.setdefault(ins[0], {}) if self.prov else None
        rpm = self._provs.setdefault(ins[1], {}) if self.prov else None
        for lr, rr, orow, w in pairs:
            nw = out.get(orow, 0) + w
            if nw:
                out[orow] = nw
            else:
                out.pop(orow, None)
            if lpm is not None:
                p = lpm.get(lr, _Prov()).union(rpm.get(rr, _Prov()),
                                               self.prov_cap)
                _punion(pm, orow, p, self.prov_cap)
        if self.prov:
            self._provs[idx] = {r: p for r, p in pm.items() if r in out}
        return out

    def _eval_linear(self, idx, op, ins):
        from dbsp_tpu.operators.basic import Minus, Neg

        out: ZDict = {}
        pm: Dict[tuple, _Prov] = {}
        for pos, i in enumerate(ins):
            z = self.integral(i)
            if z is None:
                return None
            neg = isinstance(op, Neg) or (isinstance(op, Minus) and pos == 1)
            _zadd(out, {r: -w for r, w in z.items()} if neg else z)
            if self.prov:
                for r, p in self._provs.setdefault(i, {}).items():
                    _punion(pm, r, p, self.prov_cap)
        if self.prov:
            self._provs[idx] = {r: p for r, p in pm.items() if r in out}
        return out

    def _eval_distinct(self, idx, src):
        z = self.integral(src)
        if z is None:
            return None
        out = {r: 1 for r, w in z.items() if w > 0}
        if self.prov:
            src_pm = self._provs.setdefault(src, {})
            self._provs[idx] = {r: src_pm[r] for r in out if r in src_pm}
        return out

    def _group_prov(self, idx, src, groups: Dict[tuple, List[tuple]],
                    out: ZDict, nk: int):
        """Whole-group attribution (aggregates/topk/rolling): every output
        row of a group carries the union of the group's members' prov —
        membership and value both depend on the full group."""
        if not self.prov:
            return
        src_pm = self._provs.setdefault(src, {})
        pm: Dict[tuple, _Prov] = {}
        gp: Dict[tuple, _Prov] = {}
        for k, members in groups.items():
            p = _Prov()
            for m in members:
                if m in src_pm:
                    p = p.union(src_pm[m], self.prov_cap)
            gp[k] = p
        for r in out:
            pm[r] = gp.get(r[:nk], _Prov())
        self._provs[idx] = pm

    def _eval_aggregate(self, idx, agg, nk, out_dtypes, src):
        import jax
        import jax.numpy as jnp

        z = self.integral(src)
        if z is None:
            return None
        sch = self._in_schema(src)
        src_nk = len(self.circuit.nodes[src].schema[0])
        assert src_nk == nk, (src_nk, nk)
        rows = sorted(z.keys())
        groups: Dict[tuple, List[tuple]] = {}
        for r in rows:
            groups.setdefault(r[:nk], []).append(r)
        keys = sorted(groups)
        if not keys:
            out: ZDict = {}
            self._group_prov(idx, src, groups, out, nk)
            return out
        kidx = {k: i for i, k in enumerate(keys)}
        seg = jnp.asarray(np.asarray([kidx[r[:nk]] for r in rows],
                                     np.int32))
        vcols = tuple(
            jnp.asarray(np.asarray([r[i] for r in rows]), sch[i])
            for i in range(nk, len(sch)))
        ws = jnp.asarray(np.asarray([z[r] for r in rows], np.int64))
        outs = agg.reduce(vcols, ws, seg, len(keys))
        present = np.asarray(jax.ops.segment_sum(
            jnp.where(ws > 0, 1, 0), seg,
            num_segments=len(keys))) > 0
        omats = [np.asarray(o.astype(d)) for o, d in zip(outs, out_dtypes)]
        out = {}
        for i, k in enumerate(keys):
            if present[i]:
                row = k + tuple(_pyval(m[i]) for m in omats)
                out[row] = 1
        self._group_prov(idx, src, groups, out, nk)
        return out

    def _eval_linear_aggregate(self, idx, op, src):
        import jax.numpy as jnp

        z = self.integral(src)
        if z is None:
            return None
        agg = op.agg
        nk = len(op.key_dtypes)
        groups: Dict[tuple, List[tuple]] = {}
        for r in z:
            groups.setdefault(r[:nk], []).append(r)
        sch = self._in_schema(src)
        out: ZDict = {}
        for k, members in sorted(groups.items()):
            vcols = tuple(
                jnp.asarray(np.asarray([m[i] for m in members]), sch[i])
                for i in range(nk, len(sch)))
            ws = np.asarray([z[m] for m in members], np.int64)
            weighed = agg.weigh(vcols)
            accs = tuple(
                jnp.asarray([int((np.asarray(a).astype(np.int64)
                                  * ws).sum())], jnp.int64)
                for a in weighed)
            cnt = int(ws.sum())
            if cnt <= 0:
                continue
            fin = agg.finalize(accs, jnp.asarray([cnt], jnp.int64))
            row = k + tuple(int(np.asarray(c.astype(d))[0])
                            for c, d in zip(fin, agg.out_dtypes))
            out[row] = 1
        self._group_prov(idx, src, groups, out, nk)
        return out

    def _eval_topk(self, idx, op, src):
        z = self.integral(src)
        if z is None:
            return None
        nk = len(op.schema[0])
        groups: Dict[tuple, List[tuple]] = {}
        for r, w in z.items():
            groups.setdefault(r[:nk], []).append(r)
        out: ZDict = {}
        for k, members in groups.items():
            present = sorted(r[nk:] for r in members if z[r] > 0)
            take = present[-op.k:] if op.largest else present[:op.k]
            for vals in take:
                out[k + tuple(vals)] = 1
        self._group_prov(idx, src, groups, out, nk)
        return out

    def _eval_rolling(self, idx, op, src):
        z = self.integral(src)
        if z is None:
            return None
        import jax.numpy as jnp

        rng = op.range_ms
        sch = self._in_schema(src)
        by_p: Dict[int, List[tuple]] = {}
        for r in z:
            by_p.setdefault(r[0], []).append(r)
        out: ZDict = {}
        groups: Dict[tuple, List[tuple]] = {}
        for p, members in by_p.items():
            # one output PER DISTINCT LIVE (p, t) SLOT, presence weight 1
            # — two distinct rows sharing (p, t) fill one window, not two
            # (the engine's output spine is presence-based, _diff_outputs)
            for t in sorted({r[1] for r in members if z[r] > 0}):
                win = [m for m in members if t - rng <= m[1] <= t]
                groups[(p, t)] = win
                vcols = tuple(
                    jnp.asarray(np.asarray([m[i] for m in win]), sch[i])
                    for i in range(2, len(sch)))
                ws = jnp.asarray(np.asarray([z[m] for m in win], np.int64))
                seg = jnp.zeros((len(win),), jnp.int32)
                outs = op.agg.reduce(vcols, ws, seg, 1)
                row = (p, t) + tuple(
                    int(np.asarray(o.astype(d))[0])
                    for o, d in zip(outs, op.agg.out_dtypes))
                out[row] = 1
        self._group_prov(idx, src, groups, out, 2)
        return out


# ---------------------------------------------------------------------------
# backward slicing
# ---------------------------------------------------------------------------


def _backward(node, op, targets: ZDict, ev: Evaluator,
              circuit) -> Tuple[List[Optional[ZDict]], Optional[str], bool]:
    """One node's backward rule: targets on its OUTPUT -> support per
    input (None = control/feedback edge, not followed). Returns
    (supports, note, resolved)."""
    from dbsp_tpu.operators.aggregate import AggregateOp
    from dbsp_tpu.operators.aggregate_linear import LinearAggregateOp
    from dbsp_tpu.operators.basic import Minus, Neg, Plus, SumN
    from dbsp_tpu.operators.distinct import DistinctOp
    from dbsp_tpu.operators.filter_map import FilterOp, FlatMapOp, MapOp
    from dbsp_tpu.operators.io_handles import OutputOperator
    from dbsp_tpu.operators.join import JoinOp
    from dbsp_tpu.operators.shard_op import ExchangeOp, UnshardOp
    from dbsp_tpu.operators.topk import TopKOp
    from dbsp_tpu.operators.trace_op import TraceOp
    from dbsp_tpu.operators.z1 import _PlusNamed
    from dbsp_tpu.timeseries.rolling import RollingAggregateOp
    from dbsp_tpu.timeseries.window import WindowOp

    ins = node.inputs

    if isinstance(op, (OutputOperator, TraceOp, ExchangeOp, UnshardOp)):
        return [dict(targets)], None, True
    if isinstance(op, WindowOp):
        # the bounds input is a control stream: containment is decided by
        # the watermark, but rows are not *derived from* watermark rows
        return [dict(targets), None], "bounds input treated as a parameter", \
            True
    if isinstance(op, _PlusNamed):
        sup: List[Optional[ZDict]] = []
        for i in ins:
            if circuit.nodes[i].kind == "strict_output":
                sup.append(None)
            else:
                sup.append(dict(targets))
        return sup, "integral pass-through (integrate sugar)", True
    if isinstance(op, FilterOp):
        # filters preserve rows bit-for-bit: the preimage IS the target set
        return [dict(targets)], None, True
    if isinstance(op, Neg):
        return [{r: -w for r, w in targets.items()}], None, True
    if isinstance(op, (Plus, Minus, SumN)):
        sups: List[Optional[ZDict]] = []
        ok = True
        for i in ins:
            try:
                z = ev.integral(i)
            except _Unsupported:
                z = None
            if z is None:
                sups.append(dict(targets))
                ok = False
            else:
                sups.append({r: z[r] for r in targets if r in z})
        return sups, None if ok else "an input integral was unknown", ok
    if isinstance(op, MapOp):
        pairs = ev._map_images(op, ins[0])
        if pairs is None:
            return [None], "input integral unknown (enable lineage taps)", \
                False
        z = ev.integral(ins[0])
        tset = set(targets)
        sup = {r: z[r] for r, img in pairs if img in tset}
        return [sup], None, True
    if isinstance(op, FlatMapOp):
        pairs = ev._flat_map_images(op, ins[0])
        if pairs is None:
            return [None], "input integral unknown (enable lineage taps)", \
                False
        z = ev.integral(ins[0])
        tset = set(targets)
        sup = {r: z[r] for r, imgs in pairs if any(i in tset for i in imgs)}
        return [sup], None, True
    if isinstance(op, JoinOp):
        pairs = ev._join_pairs(op, ins[0], ins[1])
        if pairs is None:
            return [None, None], \
                "a side's integral was unknown (enable lineage taps)", False
        IL, IR = ev.integral(ins[0]), ev.integral(ins[1])
        tset = set(targets)
        supL: ZDict = {}
        supR: ZDict = {}
        for lr, rr, orow, _w in pairs:
            if orow in tset:
                supL[lr] = IL[lr]
                supR[rr] = IR[rr]
        return [supL, supR], None, True
    if isinstance(op, DistinctOp):
        z = ev.integral(ins[0])
        if z is None:
            return [dict(targets)], "input integral unknown", False
        return [{r: z[r] for r in targets if r in z}], None, True
    if isinstance(op, (AggregateOp, LinearAggregateOp, TopKOp)):
        nk = len(op.key_dtypes) if not isinstance(op, TopKOp) \
            else len(op.schema[0])
        z = ev.integral(ins[0])
        if z is None:
            return [None], "input integral unknown (enable lineage taps)", \
                False
        keys = {r[:nk] for r in targets}
        return [{r: w for r, w in z.items() if r[:nk] in keys}], None, True
    if isinstance(op, RollingAggregateOp):
        z = ev.integral(ins[0])
        if z is None:
            return [None], "input integral unknown", False
        rng = op.range_ms
        slots = {(r[0], r[1]) for r in targets}
        sup = {r: w for r, w in z.items()
               if any(p == r[0] and t - rng <= r[1] <= t for p, t in slots)}
        return [sup], None, True
    return [None] * len(ins), f"unsupported operator {op.name!r}", False


def slice_view(circuit, state, view_node: int, key: Sequence,
               tables: Optional[Dict[int, str]] = None,
               view_name: Optional[str] = None,
               max_rows: Optional[int] = DEFAULT_MAX_ROWS) -> dict:
    """Backward-slice the lineage of the view rows whose key columns
    start with ``key`` — the core entry point both engines share.

    ``state`` is a :class:`HostState` / :class:`CompiledState`;
    ``tables`` maps source node index -> table name (from the catalog).
    Returns the lineage DAG report (schema ``dbsp_tpu.lineage/v1``)."""
    t0 = time.perf_counter()
    key = tuple(key)
    tables = tables or {}
    ev = Evaluator(circuit, state=state)
    try:
        I_view = ev.integral(view_node)
    except _Unsupported as e:
        I_view = None
        view_err = str(e)
    else:
        view_err = None
    if I_view is None:
        return _report(circuit, state, view_node, key, {}, {}, [],
                       tables, view_name, t0, max_rows,
                       error=view_err or
                       "view integral unknown (enable lineage taps)")
    targets = {r: w for r, w in I_view.items() if r[:len(key)] == key}
    if not targets:
        # key miss: skip the backward walk entirely — every hop would
        # intersect full-integral enumerations (join hash-joins, map
        # re-evaluations) with the empty set, under the step lock
        return _report(circuit, state, view_node, key, {}, {}, [],
                       tables, view_name, t0, max_rows)

    pend: Dict[int, ZDict] = {view_node: dict(targets)}
    hops: Dict[int, dict] = {}
    edges: List[List[int]] = []
    from dbsp_tpu.operators.io_handles import ZSetInput
    from dbsp_tpu.operators.upsert import UpsertInput

    for idx in reversed(range(len(circuit.nodes))):
        tgt = pend.get(idx)
        if tgt is None:
            continue
        node = circuit.nodes[idx]
        op = node.operator
        hop = {"node": idx, "name": op.name, "kind": type(op).__name__}
        if isinstance(op, (ZSetInput, UpsertInput)):
            hop["table"] = tables.get(idx, f"input[{idx}]")
            hop["resolved"] = True
            _hop_rows(hop, tgt, max_rows)
            hops[idx] = hop
            continue
        if not node.inputs:
            hop["note"] = f"sourceless operator {op.name!r}"
            hop["resolved"] = False
            _hop_rows(hop, tgt, max_rows)
            hops[idx] = hop
            continue
        try:
            sups, note, resolved = _backward(node, op, tgt, ev, circuit)
        except _Unsupported as e:
            sups, note, resolved = [None] * len(node.inputs), str(e), False
        hop["resolved"] = resolved
        if note:
            hop["note"] = note
        _hop_rows(hop, tgt, max_rows)
        hops[idx] = hop
        for i, sup in zip(node.inputs, sups):
            if sup is None:
                continue
            edges.append([idx, i])
            cur = pend.setdefault(i, {})
            for r, w in sup.items():
                cur[r] = w  # weights are integral weights, not additive
    return _report(circuit, state, view_node, key, targets, hops, edges,
                   tables, view_name, t0, max_rows)


def _hop_rows(hop: dict, z: ZDict, max_rows: Optional[int]) -> None:
    rows = sorted(z.items())
    hop["row_count"] = len(rows)
    cap = len(rows) if max_rows is None else max_rows
    hop["truncated"] = len(rows) > cap
    hop["rows"] = [list(r) for r, _w in rows[:cap]]
    hop["weights"] = [int(w) for _r, w in rows[:cap]]


def _report(circuit, state, view_node, key, targets, hops, edges, tables,
            view_name, t0, max_rows, error=None) -> dict:
    import jax

    inputs = {}
    resolved = error is None
    for idx, hop in hops.items():
        if "table" in hop:
            inputs[hop["table"]] = {
                "rows": hop["rows"], "weights": hop["weights"],
                "row_count": hop["row_count"],
                "truncated": hop["truncated"], "resolved": True}
        if not hop.get("resolved", True):
            resolved = False
    trows = sorted(targets.items())
    cap = len(trows) if max_rows is None else max_rows
    out = {
        "schema": LINEAGE_SCHEMA,
        "engine": getattr(state, "engine", "host"),
        "view": view_name,
        "view_node": view_node,
        "key": list(key),
        "found": bool(targets),
        "target_rows": [[list(r), int(w)] for r, w in trows[:cap]],
        "target_row_count": len(trows),
        "nodes": [hops[i] for i in sorted(hops, reverse=True)],
        "edges": edges,
        "inputs": inputs,
        "resolved": resolved,
        "latency_ms": round((time.perf_counter() - t0) * 1e3, 3),
        "host": {"cpu_count": os.cpu_count(),
                 "platform": jax.default_backend()},
    }
    if error:
        out["error"] = error
    return out


# ---------------------------------------------------------------------------
# pipeline-level entry point (server / manager / client routes)
# ---------------------------------------------------------------------------


def parse_key(key) -> tuple:
    """Accept a tuple/list, or the HTTP form: a csv of column literals
    (ints where they parse, then floats — float key columns are
    first-class dtypes — bare strings otherwise)."""
    if isinstance(key, (tuple, list)):
        return tuple(key)
    out = []
    for part in str(key).split(","):
        part = part.strip()
        if not part:
            continue
        try:
            out.append(int(part))
        except ValueError:
            try:
                out.append(float(part))
            except ValueError:
                out.append(part)
    return tuple(out)


def source_tables(circuit, catalog) -> Dict[int, str]:
    """Source node index -> catalog input-collection name."""
    tables: Dict[int, str] = {}
    for name, col in catalog.inputs.items():
        op = col.handle._op
        for node in circuit.nodes:
            if node.operator is op:
                tables[node.index] = name
    return tables


def view_node_of(circuit, catalog, view: str) -> int:
    op = catalog.output(view).handle._op
    for node in circuit.nodes:
        if node.operator is op:
            return node.inputs[0]
    raise LineageError(f"view {view!r} has no sink node in the circuit")


def slice_pipeline(handle_or_driver, catalog, view: str, key,
                   max_rows: Optional[int] = DEFAULT_MAX_ROWS) -> dict:
    """Lineage of one output row of a served pipeline: resolves the view
    through the catalog, picks the engine's state provider, and runs the
    backward slicer. Read-only; the caller owns quiescence (the HTTP
    route holds the controller's step lock)."""
    st = state_for(handle_or_driver)
    circuit = st.circuit
    report = slice_view(circuit, st, view_node_of(circuit, catalog, view),
                        parse_key(key), tables=source_tables(circuit,
                                                             catalog),
                        view_name=view, max_rows=max_rows)
    return report


def observe_query(registry, flight, report: dict) -> None:
    """Per-query observability: the gated metric families (this module is
    their ONLY registration site — tools/check_metrics.py rule 5) and one
    flight event."""
    if registry is not None:
        registry.counter(
            "dbsp_tpu_lineage_queries_total",
            "Lineage (EXPLAIN WHY) queries served, by engine mode",
            labels=("mode",)).labels(mode=report["engine"]).inc()
        registry.summary(
            "dbsp_tpu_lineage_seconds",
            "Lineage query latency (backward slice incl. state decode)"
        ).observe(report["latency_ms"] / 1e3)
    if flight is not None:
        flight.record("lineage", view=report.get("view"),
                      key=",".join(map(str, report.get("key", ()))),
                      found=report.get("found"),
                      resolved=report.get("resolved"),
                      ms=report.get("latency_ms"))


def http_query(report_fn, qs: Dict[str, list]) -> Tuple[int, Any, bool]:
    """Shared ``/lineage`` HTTP handling for the pipeline server and the
    manager proxy (ONE parser — the two surfaces cannot drift): ``qs`` is
    ``parse_qs`` output, ``report_fn(view, key, max_rows=)`` runs the
    quiesced slice. Returns ``(status, payload, dot)`` — ``dot`` means
    the payload is graphviz text, else a JSON-safe dict; usage errors and
    slicer failures map to 400."""
    view = qs.get("view", [None])[0]
    keystr = qs.get("key", [None])[0]
    if not view or keystr is None:
        return 400, {"error": "usage: ?view=<output>&key=<col1,col2,...>"
                              " [&n=<rows/hop>] [&format=dot]"}, False
    try:
        n = int(qs["n"][0]) if "n" in qs else None
        report = report_fn(view, keystr, max_rows=n)
    except Exception as e:  # noqa: BLE001 — API boundary
        return 400, {"error": f"{type(e).__name__}: {e}"}, False
    if qs.get("format", ["json"])[0] == "dot":
        return 200, lineage_dot(report), True
    return 200, report, False


def lineage_dot(report: dict) -> str:
    """Graphviz rendering of the lineage DAG: one node per hop (row
    counts in the label), edges following the backward walk, input-table
    leaves boxed."""
    lines = ["digraph lineage {", '  rankdir="RL";']
    present = {h["node"] for h in report.get("nodes", ())}
    for h in report.get("nodes", ()):
        label = f"{h['name']}\\n{h['row_count']} row(s)"
        if "table" in h:
            label = f"{h['table']}\\n{label}"
        shape = "box" if "table" in h else "ellipse"
        color = "lightblue" if "table" in h else (
            "white" if h.get("resolved", True) else "lightpink")
        lines.append(
            f'  n{h["node"]} [label="{label}", shape={shape}, '
            f'style=filled, fillcolor={color}];')
    for src, dst in report.get("edges", ()):
        if src in present and dst in present:
            lines.append(f"  n{src} -> n{dst};")
    lines.append("}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the provenance-semiring oracle
# ---------------------------------------------------------------------------


def provenance_oracle(circuit, sources: Dict[int, ZDict], view_node: int,
                      key, prov_cap: int = ORACLE_PROV_CAP) -> dict:
    """Independent full recompute with provenance tags: evaluate the
    circuit forward from ``sources`` ({source node index: input
    integral}), each input row tagged with its own id, provenance sets
    unioned through every operator (whole-group attribution at
    aggregates/topk — membership depends on the full group). Returns the
    per-source id sets supporting the view rows matching ``key``."""
    ev = Evaluator(circuit, sources=sources, prov=True, prov_cap=prov_cap)
    I_view = ev.integral(view_node)
    if I_view is None:
        raise LineageError("oracle: a source integral was not provided")
    key = tuple(parse_key(key))
    targets = {r: w for r, w in I_view.items() if r[:len(key)] == key}
    pm = ev.prov_of(view_node)
    ids = set()
    truncated = False
    for r in targets:
        p = pm.get(r)
        if p is None:
            continue
        ids |= p.ids
        truncated = truncated or p.truncated
    by_source: Dict[int, set] = {}
    for src_idx, row in ids:
        by_source.setdefault(src_idx, set()).add(row)
    return {"targets": targets, "ids_by_source": by_source,
            "truncated": truncated}


def check_against_oracle(report: dict, oracle: dict,
                         tables: Dict[int, str]) -> List[str]:
    """Agreement between a backward slice and the oracle recompute:
    identical target rows/weights and, per input table, identical row
    sets (subset when the oracle's prov sets truncated). Returns mismatch
    strings (empty = agreement)."""
    mism: List[str] = []
    got_targets = {tuple(r): w for r, w in report.get("target_rows", ())}
    want_targets = {tuple(r): w for r, w in oracle["targets"].items()}
    if report.get("target_row_count", 0) == len(
            report.get("target_rows", ())) and got_targets != want_targets:
        mism.append(
            f"target rows differ: slice={sorted(got_targets.items())!r} "
            f"oracle={sorted(want_targets.items())!r}")
    names = {idx: tables.get(idx, f"input[{idx}]")
             for idx in oracle["ids_by_source"]}
    for idx, want in oracle["ids_by_source"].items():
        name = names[idx]
        ent = report.get("inputs", {}).get(name)
        if ent is None:
            mism.append(f"slice resolved no rows for table {name!r} "
                        f"(oracle has {len(want)})")
            continue
        if ent.get("truncated"):
            mism.append(f"table {name!r}: slice rows truncated — re-run "
                        "with max_rows=None for oracle comparison")
            continue
        got = {tuple(r) for r in ent["rows"]}
        want_set = set(want)
        if oracle["truncated"]:
            if not want_set <= got:
                mism.append(f"table {name!r}: oracle rows (truncated set) "
                            f"not a subset of slice rows")
        elif got != want_set:
            only_got = sorted(got - want_set)[:4]
            only_want = sorted(want_set - got)[:4]
            mism.append(
                f"table {name!r}: slice={len(got)} oracle={len(want_set)} "
                f"rows; slice-only={only_got!r} oracle-only={only_want!r}")
    for name, ent in report.get("inputs", {}).items():
        idx = next((i for i, n in tables.items() if n == name), None)
        if ent["row_count"] and idx is not None and \
            idx not in oracle["ids_by_source"]:
            mism.append(f"table {name!r}: slice found {ent['row_count']} "
                        "rows the oracle never touched")
    return mism


# ---------------------------------------------------------------------------
# dryrun (lint front + artifact generator + CLI)
# ---------------------------------------------------------------------------


def _recap(report: dict, max_rows: Optional[int]) -> dict:
    """A capped copy of an uncapped lineage report: truncate each hop's
    (and input table's, and the target set's) row listing to ``max_rows``
    — exactly what slice_view(max_rows=...) would have served, without
    walking the circuit a second time."""
    if max_rows is None:
        return report
    out = dict(report)
    out["nodes"] = []
    for hop in report["nodes"]:
        h = dict(hop)
        h["truncated"] = h["row_count"] > max_rows
        h["rows"] = h["rows"][:max_rows]
        h["weights"] = h["weights"][:max_rows]
        out["nodes"].append(h)
    out["inputs"] = {}
    for name, ent in report["inputs"].items():
        e = dict(ent)
        e["truncated"] = e["row_count"] > max_rows
        e["rows"] = e["rows"][:max_rows]
        e["weights"] = e["weights"][:max_rows]
        out["inputs"][name] = e
    out["target_rows"] = report["target_rows"][:max_rows]
    return out


def dryrun(query: str = "q4", events: int = 4000, steps: int = 4,
           key=None, engine: str = "host", out: Optional[str] = None,
           max_rows: int = DEFAULT_MAX_ROWS,
           rate: Optional[int] = None) -> dict:
    """Build a mini Nexmark pipeline, feed it, backward-slice one output
    row, and verify the slice against the provenance-semiring oracle —
    the ``tools/lint_all.py`` front (red on divergence) and the
    ``LINEAGE_q4.json`` artifact generator (``out=``). Raises
    :class:`LineageError` on oracle divergence."""
    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.nexmark import (GeneratorConfig, NexmarkGenerator,
                                  build_inputs, queries)

    q = getattr(queries, query)

    def build(c):
        streams, handles = build_inputs(c)
        return handles, q(*streams).output()

    handle, (handles, out_handle) = Runtime.init_circuit(1, build)
    enable_taps(handle.circuit)
    driver = handle
    if engine == "compiled":
        from dbsp_tpu.compiled.driver import CompiledCircuitDriver

        driver = CompiledCircuitDriver(handle)
    if rate is None:
        # watermark/window queries need event time to cross a period
        # (q7: 10s tumbling window) — spread the same events over more
        # event time instead of feeding more events
        rate = 150 if query in ("q7",) else 1000
    gen = NexmarkGenerator(GeneratorConfig(seed=7, first_event_rate=rate))
    per = events // steps
    for i in range(steps):
        gen.feed(handles, i * per, (i + 1) * per)
        if engine == "compiled":
            driver.step()
        else:
            handle.step()
    if engine == "compiled":
        driver.flush()

    st = state_for(driver)
    circuit = handle.circuit
    tables = {}
    for name, h in zip(("persons", "auctions", "bids"), handles):
        for node in circuit.nodes:
            if node.operator is h._op:
                tables[node.index] = name
    # the view node: the one OutputOperator sink
    from dbsp_tpu.operators.io_handles import OutputOperator

    sink = next(n for n in circuit.nodes
                if isinstance(n.operator, OutputOperator))
    view_node = sink.inputs[0]
    if key is None:
        ev = Evaluator(circuit, state=st)
        I_view = ev.integral(view_node)
        if not I_view:
            raise LineageError(f"{query}: empty view — nothing to slice")
        key = sorted(I_view)[0][:1]  # first row's leading key column
    key = parse_key(key)
    # ONE uncapped slice serves both needs: the oracle comparison reads
    # it directly, the reported artifact re-caps its row lists (the cap
    # only truncates what _hop_rows lists, never what the walk computes)
    full = slice_view(circuit, st, view_node, key, tables=tables,
                      view_name=query, max_rows=None)
    report = _recap(full, max_rows)
    sources = {idx: st.source_integral(idx) for idx in tables}
    if any(v is None for v in sources.values()):
        raise LineageError("dryrun: missing source integral (taps broken?)")
    oracle = provenance_oracle(circuit, sources, view_node, key)
    mism = check_against_oracle(full, oracle, tables)
    if mism:
        raise LineageError(
            f"{query}: backward slice diverged from the provenance oracle "
            f"({len(mism)}): {mism[:4]}")
    if not full["found"]:
        raise LineageError(f"{query}: key {key!r} matched no view row")
    report["oracle"] = {"agrees": True,
                        "input_rows": {tables[i]: len(r) for i, r in
                                       oracle["ids_by_source"].items()},
                        "truncated": oracle["truncated"]}
    report["workload"] = {"query": query, "events": events, "steps": steps,
                          "engine": engine}
    report["host"]["note"] = (
        "latency measured on this CPU-only host (see host.cpu_count) — "
        "an environment figure, not a representative serving number")
    if out:
        with open(out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
            f.write("\n")
    return report


def _main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m dbsp_tpu.obs.lineage",
        description="Backward provenance slice of one Nexmark view row, "
                    "verified against the provenance-semiring oracle.")
    ap.add_argument("query", nargs="?", default="q4",
                    help="nexmark query builder name (default q4)")
    ap.add_argument("--key", default=None,
                    help="output-row key prefix, csv (default: first row)")
    ap.add_argument("--events", type=int, default=4000)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--engine", choices=("host", "compiled"),
                    default="host")
    ap.add_argument("--max-rows", type=int, default=DEFAULT_MAX_ROWS)
    ap.add_argument("--out", default=None,
                    help="also write the report JSON to this path")
    args = ap.parse_args(argv)
    report = dryrun(args.query, events=args.events, steps=args.steps,
                    key=args.key, engine=args.engine, out=args.out,
                    max_rows=args.max_rows)
    print(json.dumps(report, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main())
