"""Embedded web console (reference: ``web-ui/`` — the Next.js console's
home / SQL-editor / pipeline-management pages, reduced to one dependency-free
HTML page served by the pipeline manager at ``GET /``).

Capabilities: list programs with their version + compile status, author a
program (SQL views over declared tables), request compiles and watch the
state machine, delete programs/pipelines (conflict errors surface inline),
start/stop pipelines, push rows into a running pipeline's input
collections, and peek output views — all over the existing REST surfaces
(manager + per-pipeline circuit servers). Each pipeline row also renders
its execution mode (``host`` rows carry the recorded compiled->host
fallback reason as a tooltip), its SLO health (ok/degraded/unhealthy from
the flight-recorder watchdog, obs/slo.py), and the latest incident's
attributed cause; the Incidents/Flight/Timeline/Spikes/Profile buttons
fetch the corresponding pipeline-server routes (Timeline/Spikes = the
unified per-tick timeline and its EXPLAIN SPIKE attribution,
obs/timeline.py; Profile = the unified operator-attribution report,
obs/opprofile.py)."""

CONSOLE_HTML = r"""<!doctype html>
<html>
<head>
<meta charset="utf-8"/>
<title>dbsp_tpu console</title>
<style>
  body { font-family: -apple-system, system-ui, sans-serif; margin: 0;
         background: #0f1115; color: #e6e6e6; }
  header { padding: 14px 22px; background: #171a21;
           border-bottom: 1px solid #2a2e38; font-size: 18px; }
  header b { color: #7aa2f7; }
  main { display: grid; grid-template-columns: 1fr 1fr; gap: 18px;
         padding: 18px 22px; }
  section { background: #171a21; border: 1px solid #2a2e38;
            border-radius: 8px; padding: 14px 16px; }
  h2 { margin: 0 0 10px; font-size: 14px; text-transform: uppercase;
       letter-spacing: .08em; color: #9aa5b1; }
  textarea, input { width: 100%; box-sizing: border-box; background: #0f1115;
        color: #e6e6e6; border: 1px solid #2a2e38; border-radius: 6px;
        padding: 8px; font-family: ui-monospace, monospace; font-size: 13px; }
  textarea { min-height: 90px; }
  button { background: #2f4d8a; color: #fff; border: 0; border-radius: 6px;
           padding: 7px 14px; margin: 6px 6px 0 0; cursor: pointer; }
  button.warn { background: #8a2f2f; }
  table { width: 100%; border-collapse: collapse; font-size: 13px; }
  td, th { border-bottom: 1px solid #2a2e38; padding: 5px 8px;
           text-align: left; }
  .status-running { color: #9ece6a; } .status-failed { color: #f7768e; }
  .status-stopped { color: #9aa5b1; }
  .health-ok { color: #9ece6a; } .health-degraded { color: #e0af68; }
  .health-unhealthy { color: #f7768e; }
  pre { background: #0f1115; padding: 8px; border-radius: 6px;
        overflow: auto; max-height: 240px; }
  label { font-size: 12px; color: #9aa5b1; display: block; margin: 8px 0 3px; }
</style>
</head>
<body>
<header><b>dbsp_tpu</b> console — incremental view maintenance on TPU</header>
<main>
  <section>
    <h2>New program</h2>
    <label>name</label><input id="pname" value="demo"/>
    <label>tables (JSON: {name: {columns, dtypes, key_columns}})</label>
    <textarea id="ptables">{"events": {"columns": ["id", "category", "amount"], "dtypes": ["int64", "int64", "int64"], "key_columns": 1}}</textarea>
    <label>views (JSON: {view: "SELECT ..."})</label>
    <textarea id="psql">{"totals": "SELECT category, sum(amount) AS total FROM events GROUP BY category"}</textarea>
    <button onclick="createProgram()">Save program</button>
    <button onclick="startPipeline()">Start pipeline</button>
    <h2 style="margin-top:16px">Programs</h2>
    <table id="programs"><tr><th>name</th><th>v</th><th>compile status</th>
      <th></th></tr></table>
  </section>
  <section>
    <h2>Pipelines</h2>
    <table id="pipelines"><tr><th>name</th><th>status</th><th>mode</th>
      <th>slo</th><th>last incident</th><th>port</th><th></th></tr></table>
    <h2 style="margin-top:16px">Interact</h2>
    <label>pipeline port</label><input id="ioport"/>
    <label>input collection + rows (JSON list of lists)</label>
    <input id="icoll" value="events"/>
    <textarea id="irows">[[1, 3, 250], [2, 3, 100], [3, 7, 40]]</textarea>
    <button onclick="pushRows()">Push</button>
    <label>output view</label><input id="ocoll" value="totals"/>
    <label>row key (csv, for Why)</label><input id="okey" value="3"/>
    <button onclick="readView()">Read</button>
    <button onclick="readWhy()">Why</button>
    <button onclick="readStats()">Stats</button>
    <button onclick="readMetrics()">Metrics</button>
    <button onclick="readFleetMetrics()">Fleet metrics</button>
    <button onclick="readIncidents()">Incidents</button>
    <button onclick="readFlight()">Flight</button>
    <button onclick="readTimeline()">Timeline</button>
    <button onclick="readSpikes()">Spikes</button>
    <button onclick="readFleetHealth()">Fleet health</button>
    <button onclick="readProfile()">Profile</button>
    <button onclick="readDebug()">Debug</button>
    <pre id="io">-</pre>
  </section>
</main>
<script>
const j = (u, opt) => fetch(u, opt).then(r => r.text()).then(t => {
  try { return JSON.parse(t); } catch (e) { return t; } });
// build DOM nodes with textContent / addEventListener — server-controlled
// strings (names, errors) must never be interpolated into HTML or JS
function cell(tr, text, cls, title) {
  const td = document.createElement('td');
  if (cls) { const s = document.createElement('span'); s.className = cls;
             s.textContent = text; if (title) s.title = title;
             td.appendChild(s); }
  else td.textContent = text;
  tr.appendChild(td);
  return td;
}
function btn(td, label, cls, fn) {
  const b = document.createElement('button');
  b.textContent = label; if (cls) b.className = cls;
  b.addEventListener('click', fn);
  td.appendChild(b);
}
async function refresh() {
  const names = await j('/programs');
  const descs = await Promise.all(
      (Array.isArray(names) ? names : []).map(n => j(`/programs/${encodeURIComponent(n)}`)));
  const pt = document.getElementById('programs');
  pt.innerHTML = '<tr><th>name</th><th>v</th><th>compile status</th>' +
                 '<th></th></tr>';
  for (const d of descs) {
    const tr = document.createElement('tr');
    cell(tr, d.name); cell(tr, d.version);
    cell(tr, d.status,
         d.status === 'sql_error' ? 'status-failed'
         : d.status === 'success' ? 'status-running' : '',
         d.error ?? '');
    const td = cell(tr, '');
    btn(td, 'compile', '', () => compileProgram(d.name, d.version));
    btn(td, 'delete', 'warn', () => deleteProgram(d.name));
    pt.appendChild(tr);
  }
  const ps = await j('/pipelines');
  const tbl = document.getElementById('pipelines');
  tbl.innerHTML = '<tr><th>name</th><th>status</th><th>mode</th>' +
                  '<th>slo</th><th>last incident</th><th>port</th>' +
                  '<th></th></tr>';
  for (const p of ps) {
    const tr = document.createElement('tr');
    cell(tr, `${p.name} (v${p.program_version ?? '?'})`);
    cell(tr, p.status + (p.error ? ' — ' + p.error : ''),
         `status-${p.status}`);
    // mode=host on a compiled-default deploy is the fallback perf cliff:
    // show it, with the recorded reason as the tooltip
    cell(tr, p.mode ?? '',
         p.mode === 'host' && p.fallback_reason ? 'health-degraded' : '',
         p.fallback_reason ?? '');
    cell(tr, p.health ?? '', `health-${p.health}`,
         (p.slo && p.slo.active && p.slo.active.length)
             ? 'breached: ' + p.slo.active.join(', ') : '');
    const li = p.slo && p.slo.last_incident;
    cell(tr, li ? `${li.slo}: ${li.cause}${li.resolved ? '' : ' (open)'}`
               : '', li && !li.resolved ? 'health-unhealthy' : '');
    cell(tr, p.port ?? '');
    const td = cell(tr, '');
    btn(td, 'ckpt', '', () => checkpointPipeline(p.name));
    btn(td, 'stop', 'warn', () => stopPipeline(p.name));
    btn(td, 'delete', 'warn', () => deletePipeline(p.name));
    tbl.appendChild(tr);
    if (p.port) document.getElementById('ioport').value = p.port;
  }
}
async function compileProgram(name, version) {
  show(await j(`/programs/${encodeURIComponent(name)}/compile`,
               post({ version })));
  refresh();
}
async function deleteProgram(name) {
  show(await j(`/programs/${encodeURIComponent(name)}`,
               { method: 'DELETE' }));
  refresh();
}
async function deletePipeline(name) {
  show(await j(`/pipelines/${encodeURIComponent(name)}`,
               { method: 'DELETE' }));
  refresh();
}
async function createProgram() {
  const body = { name: val('pname'), tables: JSON.parse(val('ptables')),
                 sql: JSON.parse(val('psql')) };
  show(await j('/programs', post(body)));
  refresh();
}
async function startPipeline() {
  show(await j('/pipelines',
               post({ name: val('pname'), program: val('pname') })));
  refresh();
}
async function stopPipeline(name) {
  show(await j(`/pipelines/${encodeURIComponent(name)}/shutdown`, post({})));
  refresh();
}
// durability (dbsp_tpu.checkpoint): write one generation now; the reply
// carries the checkpointed tick + generation (or the config error)
async function checkpointPipeline(name) {
  show(await j(`/pipelines/${encodeURIComponent(name)}/checkpoint`,
               post({})));
  refresh();
}
async function pushRows() {
  const rows = JSON.parse(val('irows'))
      .map(r => JSON.stringify({ insert: r })).join('\n');
  show(await fetch(
      `http://127.0.0.1:${val('ioport')}/input_endpoint/${val('icoll')}?format=json`,
      { method: 'POST', body: rows }).then(r => r.text()));
}
async function readView() {
  show(await fetch(
      `http://127.0.0.1:${val('ioport')}/output_endpoint/${val('ocoll')}?format=json`)
      .then(r => r.text()) || '(empty)');
}
async function readStats() {
  show(await j(`http://127.0.0.1:${val('ioport')}/stats`));
}
// registry-backed observability (dbsp_tpu.obs): per-pipeline Prometheus
// text and the manager's fleet-wide aggregate
async function readMetrics() {
  show(await fetch(`http://127.0.0.1:${val('ioport')}/metrics`)
      .then(r => r.text()));
}
async function readFleetMetrics() {
  show(await fetch('/metrics').then(r => r.text()));
}
// flight recorder + SLO watchdog (dbsp_tpu.obs.flight / .slo): the raw
// event ring and the captured incidents with their attributed causes
async function readIncidents() {
  show(await j(`http://127.0.0.1:${val('ioport')}/incidents?window=0`));
}
async function readFlight() {
  show(await j(`http://127.0.0.1:${val('ioport')}/flight?n=64`));
}
// unified timeline + EXPLAIN SPIKE (dbsp_tpu.obs.timeline): tick
// latency/rows/queue depth + flight events + freshness in one ring, and
// the outlier ticks attributed against the robust rolling baseline
async function readTimeline() {
  show(await j(`http://127.0.0.1:${val('ioport')}/timeline?n=64`));
}
async function readSpikes() {
  show(await j(`http://127.0.0.1:${val('ioport')}/spikes`));
}
async function readFleetHealth() {
  show(await j('/health'));
}
// operator attribution (dbsp_tpu.obs.opprofile): the unified /profile
// report — continuous per-operator timings on host pipelines, static
// per-node XLA cost analysis on compiled ones (append ?ticks=N on the
// pipeline port for the quiesced measured mode)
async function readProfile() {
  show(await j(`http://127.0.0.1:${val('ioport')}/profile`));
}
// row-level lineage (dbsp_tpu.obs.lineage): why is this row in my view?
// — the backward provenance DAG down to concrete input-table rows
async function readWhy() {
  show(await j(`http://127.0.0.1:${val('ioport')}/lineage?view=` +
      `${encodeURIComponent(val('ocoll'))}&key=` +
      `${encodeURIComponent(val('okey'))}`));
}
// the one-shot diagnostics bundle: attach this JSON to the bug report
async function readDebug() {
  show(await j(`http://127.0.0.1:${val('ioport')}/debug`));
}
const val = id => document.getElementById(id).value;
const post = b => ({ method: 'POST', body: JSON.stringify(b) });
const show = x => document.getElementById('io').textContent =
    typeof x === 'string' ? x : JSON.stringify(x, null, 1);
refresh(); setInterval(refresh, 4000);
</script>
</body>
</html>
"""
