"""A small SQL parser: SELECT queries over registered streams.

Reference context: the reference's SQL frontend is an out-of-tree Apache
Calcite compiler invoked as a subprocess (SURVEY.md L5; the submodule is not
even checked out there). This is the smallest viable in-tree equivalent: a
hand-rolled tokenizer + recursive-descent parser for the subset that covers
incremental view maintenance over streams:

    SELECT [DISTINCT] expr [AS name], ...
    FROM source [alias]
    { [[LEFT] [INNER] JOIN source [alias] ON col = col
       | JOIN source [alias] ON col BETWEEN expr AND expr] }...
    [WHERE predicate]
    [GROUP BY col, ...] [HAVING predicate]
    [ORDER BY col [ASC|DESC], ...] [LIMIT n]

    query := select | query UNION [ALL] query | query EXCEPT query
           | query INTERSECT query            (left-associative; parenthesize
                                               to control grouping)
    source := table | ( query ) alias         (FROM-subqueries)

with integer/float/string/'NULL' literals, + - * / %, comparisons, BETWEEN,
AND/OR/NOT, ``IS [NOT] NULL``, ``[NOT] IN (literal-list | SELECT ...)``,
``[NOT] EXISTS (SELECT ...)`` (correlated equality predicates decorrelate
onto semijoin keys), ``[NOT] LIKE 'pat'`` over strings, aggregates
COUNT(*) / COUNT / SUM / MIN / MAX / AVG, and scalar subqueries
``(SELECT <aggregate> FROM ...)`` as comparison operands. The planner
(``sql/planner.py``) lowers the AST onto circuit operators — ORDER BY +
LIMIT onto top-K, LEFT JOIN onto join + antijoin, BETWEEN joins onto
range joins, join chains onto left-deep bilinear joins, set operations
onto plus/neg + distinct/semijoin/antijoin — so every query is maintained
incrementally like any hand-built circuit.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Tuple, Union

TOKEN_RE = re.compile(
    r"\s*(?:(?P<str>'(?:[^']|'')*')|(?P<num>\d+\.\d+|\d+)"
    r"|(?P<id>[A-Za-z_][A-Za-z_0-9]*)"
    r"|(?P<op><=|>=|<>|!=|=|<|>|\+|-|\*|/|%|\(|\)|,|\.))")

KEYWORDS = {"select", "distinct", "from", "join", "on", "where", "group",
            "by", "as", "and", "or", "not", "count", "sum", "min", "max",
            "avg", "having", "order", "limit", "asc", "desc", "left",
            "outer", "inner", "between", "union", "except", "intersect",
            "all", "null", "is", "in", "exists", "like"}


def tokenize(sql: str) -> List[Tuple[str, str]]:
    out, pos = [], 0
    while pos < len(sql):
        m = TOKEN_RE.match(sql, pos)
        if not m:
            if sql[pos:].strip():
                raise SyntaxError(f"bad SQL at: {sql[pos:pos+20]!r}")
            break
        pos = m.end()
        if m.group("str"):
            # SQL string literal: '' escapes a quote
            out.append(("str", m.group("str")[1:-1].replace("''", "'")))
        elif m.group("num"):
            out.append(("num", m.group("num")))
        elif m.group("id"):
            word = m.group("id")
            out.append(("kw", word.lower()) if word.lower() in KEYWORDS
                       else ("id", word))
        else:
            out.append(("op", m.group("op")))
    return out


# -- AST --------------------------------------------------------------------


@dataclasses.dataclass
class Col:
    table: Optional[str]
    name: str


@dataclasses.dataclass
class Lit:
    value: Union[int, float, str, None]  # None == SQL NULL


@dataclasses.dataclass
class BinOp:
    op: str
    left: "Expr"
    right: "Expr"


@dataclasses.dataclass
class NotOp:
    expr: "Expr"


@dataclasses.dataclass
class Agg:
    fn: str               # count|sum|min|max|avg
    arg: Optional["Expr"]  # None for COUNT(*)


@dataclasses.dataclass
class Subquery:
    select: "Select"      # scalar subquery (single aggregate, no grouping)


@dataclasses.dataclass
class IsNull:
    """``expr IS [NOT] NULL``."""

    expr: "Expr"
    negated: bool = False


@dataclasses.dataclass
class InList:
    """``expr [NOT] IN (lit, lit, ...)``."""

    expr: "Expr"
    values: List["Lit"] = dataclasses.field(default_factory=list)
    negated: bool = False


@dataclasses.dataclass
class InSubquery:
    """``expr [NOT] IN (SELECT single_column ...)`` — lowered onto the
    incremental semijoin/antijoin pair (operators/semijoin.py)."""

    expr: "Expr"
    select: "Query" = None
    negated: bool = False


@dataclasses.dataclass
class ExistsOp:
    """``[NOT] EXISTS (SELECT ... [WHERE sub.c = outer.c ...])`` — the
    correlated equality predicates are decorrelated onto semijoin keys."""

    select: "Query"
    negated: bool = False


@dataclasses.dataclass
class LikeOp:
    """``expr [NOT] LIKE 'pattern'`` over string-typed expressions."""

    expr: "Expr"
    pattern: str = ""
    negated: bool = False


Expr = Union[Col, Lit, BinOp, NotOp, Agg, Subquery, IsNull, InList,
             InSubquery, ExistsOp, LikeOp]


@dataclasses.dataclass
class SelectItem:
    expr: Expr
    alias: Optional[str]


@dataclasses.dataclass
class TableRef:
    name: str
    alias: str


@dataclasses.dataclass
class OrderItem:
    col: Col
    desc: bool


@dataclasses.dataclass
class RangeOn:
    """JOIN ... ON <right col> BETWEEN <expr over left> AND <expr over left>."""

    col: Col
    lo: Expr
    hi: Expr


@dataclasses.dataclass
class SubSource:
    """A FROM-subquery: ``FROM (query) alias``."""

    select: "Query"
    alias: str


Source = Union[TableRef, SubSource]


@dataclasses.dataclass
class Join:
    """One JOIN clause in a (left-deep) chain."""

    table: Source
    on: Optional[Tuple[Col, Col]]      # equi-join columns
    range: Optional[RangeOn]           # or BETWEEN bounds
    left: bool = False                 # LEFT [OUTER] JOIN


@dataclasses.dataclass
class Select:
    items: List[SelectItem]
    distinct: bool
    table: Source
    joins: List[Join]
    where: Optional[Expr]
    group_by: List[Col]
    having: Optional[Expr] = None
    order_by: List[OrderItem] = dataclasses.field(default_factory=list)
    limit: Optional[int] = None


@dataclasses.dataclass
class SetOp:
    """UNION [ALL] / EXCEPT / INTERSECT of two queries."""

    op: str                # union | except | intersect
    all: bool              # bag semantics (UNION ALL only)
    left: "Query"
    right: "Query"


Query = Union[Select, SetOp]


class Parser:
    def __init__(self, tokens: List[Tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else ("eof", "")

    def next(self):
        t = self.peek()
        self.i += 1
        return t

    def expect(self, kind, val=None):
        t = self.next()
        if t[0] != kind or (val is not None and t[1] != val):
            raise SyntaxError(f"expected {val or kind}, got {t}")
        return t

    def accept(self, kind, val=None):
        t = self.peek()
        if t[0] == kind and (val is None or t[1] == val):
            self.i += 1
            return True
        return False

    # -- grammar ------------------------------------------------------------
    def parse_select(self):
        s = self.query_body()
        if self.peek()[0] != "eof":
            raise SyntaxError(f"trailing tokens: {self.toks[self.i:]}")
        return s

    def query_body(self):
        """select (UNION [ALL] | EXCEPT | INTERSECT) select ... —
        left-associative (parenthesize operands to control grouping)."""
        node = self.query_operand()
        while self.peek()[0] == "kw" and \
                self.peek()[1] in ("union", "except", "intersect"):
            op = self.next()[1]
            all_ = self.accept("kw", "all")
            if all_ and op != "union":
                raise SyntaxError(f"{op.upper()} ALL is not supported")
            node = SetOp(op, all_, node, self.query_operand())
        return node

    def query_operand(self):
        if self.peek() == ("op", "("):
            save = self.i
            self.next()
            if self.peek() == ("kw", "select"):
                s = self.query_body()
                self.expect("op", ")")
                return s
            self.i = save  # parenthesized expression, not a subquery
        return self.select_body()

    def table_source(self) -> Source:
        """table [alias] | ( query ) alias"""
        if self.accept("op", "("):
            sel = self.query_body()
            self.expect("op", ")")
            alias = self.expect("id")[1]
            return SubSource(sel, alias)
        return self.table_ref()

    def select_body(self) -> Select:
        self.expect("kw", "select")
        distinct = self.accept("kw", "distinct")
        items = [self.select_item()]
        while self.accept("op", ","):
            items.append(self.select_item())
        self.expect("kw", "from")
        table = self.table_source()
        joins: List[Join] = []
        while self.peek() in (("kw", "left"), ("kw", "join"),
                              ("kw", "inner")):
            join_left = False
            if self.accept("kw", "left"):
                self.accept("kw", "outer")
                join_left = True
            else:
                self.accept("kw", "inner")
            self.expect("kw", "join")
            jtable = self.table_source()
            self.expect("kw", "on")
            left = self.column()
            if self.accept("kw", "between"):
                lo = self.additive()
                self.expect("kw", "and")
                hi = self.additive()
                joins.append(Join(jtable, None, RangeOn(left, lo, hi),
                                  join_left))
            else:
                self.expect("op", "=")
                right = self.column()
                joins.append(Join(jtable, (left, right), None, join_left))
        where = None
        if self.accept("kw", "where"):
            where = self.disjunction()
        group_by: List[Col] = []
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            group_by.append(self.column())
            while self.accept("op", ","):
                group_by.append(self.column())
        having = None
        if self.accept("kw", "having"):
            having = self.disjunction()
        order_by: List[OrderItem] = []
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            while True:
                col = self.column()
                desc = bool(self.accept("kw", "desc"))
                if not desc:
                    self.accept("kw", "asc")
                order_by.append(OrderItem(col, desc))
                if not self.accept("op", ","):
                    break
        limit = None
        if self.accept("kw", "limit"):
            limit = int(self.expect("num")[1])
        return Select(items, distinct, table, joins, where, group_by,
                      having, order_by, limit)

    def select_item(self) -> SelectItem:
        if self.peek() == ("op", "*"):
            self.next()
            return SelectItem(Col(None, "*"), None)
        e = self.disjunction()
        alias = None
        if self.accept("kw", "as"):
            alias = self.expect("id")[1]
        return SelectItem(e, alias)

    def table_ref(self) -> TableRef:
        name = self.expect("id")[1]
        alias = name
        if self.peek()[0] == "id":
            alias = self.next()[1]
        return TableRef(name, alias)

    def column(self) -> Col:
        first = self.expect("id")[1]
        if self.accept("op", "."):
            return Col(first, self.expect("id")[1])
        return Col(None, first)

    # precedence: OR < AND < NOT < comparison < add < mul < atom
    def disjunction(self) -> Expr:
        e = self.conjunction()
        while self.accept("kw", "or"):
            e = BinOp("or", e, self.conjunction())
        return e

    def conjunction(self) -> Expr:
        e = self.negation()
        while self.accept("kw", "and"):
            e = BinOp("and", e, self.negation())
        return e

    def negation(self) -> Expr:
        if self.accept("kw", "not"):
            return NotOp(self.negation())
        if self.peek() == ("kw", "exists"):
            self.next()
            self.expect("op", "(")
            sub = self.query_body()
            self.expect("op", ")")
            return ExistsOp(sub)
        return self.comparison()

    def comparison(self) -> Expr:
        e = self.additive()
        t = self.peek()
        if t[0] == "op" and t[1] in ("=", "<>", "!=", "<", "<=", ">", ">="):
            self.next()
            return BinOp(t[1], e, self.additive())
        if t == ("kw", "between"):  # sugar: e BETWEEN a AND b
            self.next()
            lo = self.additive()
            self.expect("kw", "and")
            hi = self.additive()
            return BinOp("and", BinOp(">=", e, lo), BinOp("<=", e, hi))
        if t == ("kw", "is"):  # e IS [NOT] NULL
            self.next()
            negated = self.accept("kw", "not")
            self.expect("kw", "null")
            return IsNull(e, negated)
        negated = False
        if t == ("kw", "not"):  # e NOT IN / e NOT LIKE
            save = self.i
            self.next()
            if self.peek() not in (("kw", "in"), ("kw", "like")):
                self.i = save
                return e
            negated = True
            t = self.peek()
        if t == ("kw", "in"):
            self.next()
            self.expect("op", "(")
            if self.peek() == ("kw", "select"):
                sub = self.query_body()
                self.expect("op", ")")
                return InSubquery(e, sub, negated)
            vals = [self._literal()]
            while self.accept("op", ","):
                vals.append(self._literal())
            self.expect("op", ")")
            return InList(e, vals, negated)
        if t == ("kw", "like"):
            self.next()
            pat = self.expect("str")[1]
            return LikeOp(e, pat, negated)
        return e

    def _literal(self) -> Lit:
        t = self.next()
        if t[0] == "num":
            return Lit(float(t[1]) if "." in t[1] else int(t[1]))
        if t[0] == "str":
            return Lit(t[1])
        if t == ("kw", "null"):
            return Lit(None)
        if t == ("op", "-"):
            n = self.expect("num")[1]
            return Lit(-(float(n) if "." in n else int(n)))
        raise SyntaxError(f"expected literal, got {t}")

    def additive(self) -> Expr:
        e = self.multiplicative()
        while True:
            t = self.peek()
            if t[0] == "op" and t[1] in ("+", "-"):
                self.next()
                e = BinOp(t[1], e, self.multiplicative())
            else:
                return e

    def multiplicative(self) -> Expr:
        e = self.atom()
        while True:
            t = self.peek()
            if t[0] == "op" and t[1] in ("*", "/", "%"):
                self.next()
                e = BinOp(t[1], e, self.atom())
            else:
                return e

    def atom(self) -> Expr:
        t = self.peek()
        if t[0] == "num":
            self.next()
            return Lit(float(t[1]) if "." in t[1] else int(t[1]))
        if t[0] == "str":
            self.next()
            return Lit(t[1])
        if t == ("kw", "null"):
            self.next()
            return Lit(None)
        if t[0] == "op" and t[1] == "(":
            self.next()
            if self.peek() == ("kw", "select"):  # scalar subquery
                sub = self.query_body()
                self.expect("op", ")")
                return Subquery(sub)
            e = self.disjunction()
            self.expect("op", ")")
            return e
        if t[0] == "op" and t[1] == "-":
            self.next()
            return BinOp("-", Lit(0), self.atom())
        if t[0] == "kw" and t[1] in ("count", "sum", "min", "max", "avg"):
            fn = self.next()[1]
            self.expect("op", "(")
            if fn == "count" and self.accept("op", "*"):
                arg = None
            else:
                arg = self.disjunction()
            self.expect("op", ")")
            return Agg(fn, arg)
        if t[0] == "id":
            return self.column()
        raise SyntaxError(f"unexpected token {t}")


def parse(sql: str) -> Query:
    return Parser(tokenize(sql)).parse_select()
