from dbsp_tpu.sql.parser import parse
from dbsp_tpu.sql.planner import SqlContext, SqlError

__all__ = ["parse", "SqlContext", "SqlError"]
