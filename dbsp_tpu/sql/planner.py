"""SQL planner: lowers parsed SELECTs onto incremental circuit operators.

The in-tree stand-in for the reference's out-of-tree Calcite->Rust compiler
(``pipeline_manager/src/compiler.rs`` invokes it as a subprocess; SURVEY.md
L5): here SQL plans straight into the same Stream operators hand-built
queries use, so every registered view is incrementally maintained — inserts
and retractions on base tables propagate deltas through WHERE/JOIN/GROUP BY.

Lowering map:
    WHERE                -> filter_rows (columnar predicate)
    JOIN ... ON a = b    -> index_by + incremental bilinear join
    GROUP BY + agg       -> index_by + incremental aggregate (one per agg,
                            joined on the group key — reference's multi-agg
                            plans share the same shape)
    DISTINCT             -> incremental distinct
    plain SELECT         -> map_rows projection
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from dbsp_tpu.circuit.builder import Circuit, Stream
from dbsp_tpu.operators.aggregate import Max, Min
from dbsp_tpu.operators.aggregate_linear import (
    LinearAverage as Average, LinearCount as Count, LinearSum as Sum)
from dbsp_tpu.sql import parser as P

AGG_CLASSES = {"count": Count, "sum": Sum, "min": Min, "max": Max,
               "avg": Average}


class SqlError(ValueError):
    pass


class _Scope:
    """Column-name resolution over a stream's (key+val) columns."""

    def __init__(self, names: List[str], dtypes: List):
        self.names = list(names)
        self.dtypes = list(dtypes)

    def index_of(self, col: P.Col) -> int:
        want = f"{col.table}.{col.name}" if col.table else col.name
        hits = [i for i, n in enumerate(self.names)
                if n == want or (col.table is None and
                                 n.split(".")[-1] == col.name)]
        if not hits:
            raise SqlError(f"unknown column {want}; have {self.names}")
        if len(hits) > 1:
            raise SqlError(f"ambiguous column {want}")
        return hits[0]


def _compile_expr(expr, scope: _Scope):
    """Expr -> fn(flat_cols_tuple) -> array; plus the result dtype."""

    def fn(cols):
        return _eval(expr, scope, cols)

    samples = tuple(jnp.zeros((1,), d) for d in scope.dtypes)
    out_dtype = np.asarray(fn(samples)).dtype
    return fn, out_dtype


def _eval(expr, scope: _Scope, cols):
    if isinstance(expr, P.Lit):
        return jnp.asarray(expr.value)
    if isinstance(expr, P.Col):
        return cols[scope.index_of(expr)]
    if isinstance(expr, P.NotOp):
        return ~_eval(expr.expr, scope, cols)
    if isinstance(expr, P.BinOp):
        a = _eval(expr.left, scope, cols)
        b = _eval(expr.right, scope, cols)
        op = expr.op
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op in ("/", "%"):
            if jnp.issubdtype(jnp.result_type(a, b), jnp.integer):
                # SQL/reference semantics: division truncates toward zero
                # (-7/2 == -3) and % is the matching remainder (-7%2 == -1),
                # so a == (a/b)*b + a%b holds — unlike Python/JAX floored
                # //+%; matches the Average aggregator's truncating reduce
                q = a // b
                r = a - q * b
                q = jnp.where((r != 0) & ((a < 0) != (b < 0)), q + 1, q)
                return q if op == "/" else a - q * b
            return a / b if op == "/" else a % b
        if op == "=":
            return a == b
        if op in ("<>", "!="):
            return a != b
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
        if op == ">":
            return a > b
        if op == ">=":
            return a >= b
        if op == "and":
            return a & b
        if op == "or":
            return a | b
    raise SqlError(f"cannot evaluate {expr}")


class SqlContext:
    """Registry of named streams + the SQL entry point."""

    def __init__(self, circuit: Circuit):
        self.circuit = circuit
        self.tables: Dict[str, Tuple[Stream, List[str]]] = {}

    def register_table(self, name: str, stream: Stream,
                       columns: List[str]) -> None:
        schema = getattr(stream, "schema", None)
        assert schema is not None, "registered streams need schema metadata"
        ncols = len(schema[0]) + len(schema[1])
        assert len(columns) == ncols, (
            f"{name}: {len(columns)} column names for {ncols} columns")
        self.tables[name] = (stream, list(columns))

    # -- planning -----------------------------------------------------------
    def query(self, sql: str) -> Stream:
        ast = P.parse(sql)
        stream, scope = self._plan_from(ast)
        if ast.where is not None:
            pred, dt = _compile_expr(ast.where, scope)
            if dt != np.bool_:
                raise SqlError("WHERE must be boolean")
            stream = stream.filter_rows(
                lambda k, v, _p=pred: _p((*k, *v)), name="sql-where")
        has_aggs = any(isinstance(i.expr, P.Agg) for i in ast.items)
        if has_aggs or ast.group_by:
            stream = self._plan_aggregate(ast, stream, scope)
        else:
            stream = self._plan_project(ast, stream, scope)
        if ast.distinct:
            stream = stream.distinct()
        return stream

    def _table_scope(self, ref: P.TableRef) -> Tuple[Stream, _Scope]:
        if ref.name not in self.tables:
            raise SqlError(f"unknown table {ref.name}")
        stream, cols = self.tables[ref.name]
        schema = stream.schema
        dtypes = [*schema[0], *schema[1]]
        return stream, _Scope([f"{ref.alias}.{c}" for c in cols], dtypes)

    def _plan_from(self, ast: P.Select) -> Tuple[Stream, _Scope]:
        left, ls = self._table_scope(ast.table)
        if ast.join is None:
            return left, ls
        right, rs = self._table_scope(ast.join)
        lcol, rcol = ast.join_on
        # resolve which side each ON column belongs to
        try:
            li = ls.index_of(lcol)
        except SqlError:
            lcol, rcol = rcol, lcol
            li = ls.index_of(lcol)
        ri = rs.index_of(rcol)
        # promote mixed-dtype ON columns to one key dtype; index_by/map_rows
        # cast their outputs to the declared schema, so both traces carry the
        # same key dtype and lex_probe never truncates probe keys
        key_dt = jnp.result_type(ls.dtypes[li], rs.dtypes[ri])

        def rekey(idx, n):
            def key_fn(k, v, _i=idx):
                return ((*k, *v)[_i],)

            def val_fn(k, v, _n=n):
                return tuple((*k, *v))

            return key_fn, val_fn

        lk, lv = rekey(li, len(ls.names))
        rk, rv = rekey(ri, len(rs.names))
        lkeyed = left.index_by(lk, (key_dt,), val_fn=lv,
                               val_dtypes=tuple(ls.dtypes), name="sql-lkey")
        rkeyed = right.index_by(rk, (key_dt,), val_fn=rv,
                                val_dtypes=tuple(rs.dtypes), name="sql-rkey")
        joined = lkeyed.join_index(
            rkeyed, lambda k, lvs, rvs: (k, (*lvs, *rvs)),
            (key_dt,), (*ls.dtypes, *rs.dtypes), name="sql-join")
        scope = _Scope(["__jk__", *ls.names, *rs.names],
                       [key_dt, *ls.dtypes, *rs.dtypes])
        return joined, scope

    def _plan_project(self, ast: P.Select, stream: Stream, scope: _Scope
                      ) -> Stream:
        if len(ast.items) == 1 and isinstance(ast.items[0].expr, P.Col) \
                and ast.items[0].expr.name == "*":
            return stream
        fns, dts = [], []
        for item in ast.items:
            fn, dt = _compile_expr(item.expr, scope)
            fns.append(fn)
            dts.append(dt)

        def project(k, v):
            cols = (*k, *v)
            outs = tuple(jnp.broadcast_to(f(cols), cols[0].shape)
                         for f in fns)
            return outs, ()

        return stream.map_rows(project, tuple(dts), (), name="sql-project")

    def _plan_aggregate(self, ast: P.Select, stream: Stream, scope: _Scope
                        ) -> Stream:
        group_idx = [scope.index_of(c) for c in ast.group_by]
        key_dts = [scope.dtypes[i] for i in group_idx] or [jnp.int64]

        aggs: List[Tuple[int, P.Agg]] = []
        for pos, item in enumerate(ast.items):
            if isinstance(item.expr, P.Agg):
                aggs.append((pos, item.expr))
            elif isinstance(item.expr, P.Col):
                if scope.index_of(item.expr) not in group_idx:
                    raise SqlError(
                        f"{item.expr} must appear in GROUP BY or an aggregate")
            else:
                raise SqlError("non-aggregate select items must be columns")

        def keyed_stream(agg: P.Agg) -> Stream:
            if agg.arg is None:
                arg_fn, arg_dt = (lambda cols: jnp.ones_like(cols[0])), \
                    np.dtype(np.int64)
            else:
                arg_fn, arg_dt = _compile_expr(agg.arg, scope)

            def mapper(k, v, _f=arg_fn):
                cols = (*k, *v)
                keys = tuple(cols[i] for i in group_idx) or \
                    (jnp.zeros_like(cols[0]),)
                return keys, (jnp.broadcast_to(_f(cols), cols[0].shape),)

            return stream.map_rows(mapper, tuple(key_dts), (arg_dt,),
                                   name="sql-keyed")

        results = []
        for pos, agg in aggs:
            ks = keyed_stream(agg)
            cls = AGG_CLASSES[agg.fn]
            inst = cls() if agg.fn == "count" else cls(0)
            results.append(ks.aggregate(inst, name=f"sql-{agg.fn}"))
        combined = results[0]
        for extra in results[1:]:
            n = len(combined.schema[1])
            combined = combined.join_index(
                extra, lambda k, a, b: (k, (*a, *b)),
                tuple(key_dts),
                (*combined.schema[1], *extra.schema[1]), name="sql-aggjoin")

        # order output columns as selected: group cols come from the key
        agg_positions = {pos: i for i, (pos, _) in enumerate(aggs)}

        def finalize(k, v):
            outs = []
            for pos, item in enumerate(ast.items):
                if pos in agg_positions:
                    outs.append(v[agg_positions[pos]])
                else:
                    outs.append(k[group_idx.index(
                        scope.index_of(item.expr))])
            return tuple(outs), ()

        out_dts = []
        for pos, item in enumerate(ast.items):
            if pos in agg_positions:
                out_dts.append(jnp.int64)
            else:
                out_dts.append(scope.dtypes[scope.index_of(item.expr)])
        return combined.map_rows(finalize, tuple(out_dts), (),
                                 name="sql-finalize")
