"""SQL planner: lowers parsed SELECTs onto incremental circuit operators.

The in-tree stand-in for the reference's out-of-tree Calcite->Rust compiler
(``pipeline_manager/src/compiler.rs`` invokes it as a subprocess; SURVEY.md
L5): here SQL plans straight into the same Stream operators hand-built
queries use, so every registered view is incrementally maintained — inserts
and retractions on base tables propagate deltas through WHERE/JOIN/GROUP BY.

Lowering map:
    WHERE                -> filter_rows (columnar predicate)
    JOIN ... ON a = b    -> index_by + incremental bilinear join
    LEFT JOIN            -> inner join ∪ (antijoined left rows padded with
                            the NULL marker, iinfo.min — see NULL_INT)
    JOIN ON r BETWEEN l+c1 AND l+c2 -> incremental relative range join
                            (operators/join_range.py)
    GROUP BY + agg       -> index_by + incremental aggregate (one per agg,
                            joined on the group key — reference's multi-agg
                            plans share the same shape)
    HAVING               -> filter over the joined aggregate columns
    DISTINCT             -> incremental distinct
    ORDER BY ... LIMIT n -> global top-K (operators/topk.py); ORDER BY
                            without LIMIT is a no-op (Z-sets are unordered)
    scalar subqueries    -> planned standalone, cross-joined on a unit key,
                            then referenced like columns in WHERE
    plain SELECT         -> map_rows projection
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from dbsp_tpu.circuit.builder import Circuit, Stream
from dbsp_tpu.operators.aggregate import Max, Min
from dbsp_tpu.operators.aggregate_linear import (
    LinearAverage as Average, LinearCount as Count, LinearSum as Sum)
from dbsp_tpu.sql import parser as P

AGG_CLASSES = {"count": Count, "sum": Sum, "min": Min, "max": Max,
               "avg": Average}


@dataclasses.dataclass(frozen=True)
class _SqlNullAgg:
    """NULL-aware SQL aggregation for nullable (outer-joined) columns:
    rows whose argument carries the NULL_INT marker are ignored, and a
    group with no non-NULL rows aggregates to NULL (count: to 0) — SQL
    semantics sqlite also implements. Only used when the query has a LEFT
    JOIN (other queries keep the linear fast path)."""

    fn: str = "sum"
    out_dtypes = (jnp.int64,)
    insert_combinable = False

    @property
    def name(self):
        return f"sql-null-{self.fn}"

    def reduce(self, val_cols, weights, seg, num_segments):
        import jax

        v = val_cols[0]
        null = NULL_INT(v.dtype)
        w = jnp.where(v == null, 0, weights)
        wpos = jnp.maximum(w, 0)
        cnt = jax.ops.segment_sum(wpos, seg, num_segments=num_segments)
        if self.fn == "count":
            return (cnt,)  # COUNT of all-NULL is 0, not NULL
        if self.fn == "sum":
            out = jax.ops.segment_sum(v * wpos, seg,
                                      num_segments=num_segments)
        elif self.fn == "min":
            hi = jnp.iinfo(v.dtype).max
            out = jax.ops.segment_min(jnp.where(w > 0, v, hi), seg,
                                      num_segments=num_segments)
        elif self.fn == "max":
            lo = jnp.iinfo(v.dtype).min
            out = jax.ops.segment_max(jnp.where(w > 0, v, lo), seg,
                                      num_segments=num_segments)
        else:  # avg — truncating division, matching Average
            s = jax.ops.segment_sum(v * wpos, seg,
                                    num_segments=num_segments)
            c = jnp.maximum(cnt, 1)
            out = jnp.where(s >= 0, s // c, -((-s) // c))
        return (jnp.where(cnt > 0, out, jnp.asarray(null, out.dtype)),)

# SQL NULL marker for outer-join padding: the dtype's MINIMUM (the maximum
# is the engine's dead-row sentinel). Documented engine-wide convention —
# the reference's nullable columns become (value | NULL_INT) here.
def NULL_INT(dtype):
    return int(np.iinfo(np.dtype(dtype)).min)


class SqlError(ValueError):
    pass


class _Scope:
    """Column-name resolution over a stream's (key+val) columns.

    ``nullable`` holds the indices of columns that may carry the NULL_INT
    marker (outer-join padding) — aggregate planning keys NULL-awareness
    off it, and it propagates through joins, subqueries, and set ops."""

    def __init__(self, names: List[str], dtypes: List, nullable=()):
        self.names = list(names)
        self.dtypes = list(dtypes)
        self.nullable = frozenset(nullable)

    def index_of(self, col: P.Col) -> int:
        want = f"{col.table}.{col.name}" if col.table else col.name
        hits = [i for i, n in enumerate(self.names)
                if n == want or (col.table is None and
                                 n.split(".")[-1] == col.name)]
        if not hits:
            raise SqlError(f"unknown column {want}; have {self.names}")
        if len(hits) > 1:
            raise SqlError(f"ambiguous column {want}")
        return hits[0]


def _item_names(items) -> List[str]:
    out = []
    for i, item in enumerate(items):
        if item.alias:
            out.append(item.alias)
        elif isinstance(item.expr, P.Col):
            out.append(f"{item.expr.table}.{item.expr.name}"
                       if item.expr.table else item.expr.name)
        else:
            out.append(f"col{i}")
    return out


def _collect_aggs(expr) -> List[P.Agg]:
    if isinstance(expr, P.Agg):
        return [expr]
    if isinstance(expr, P.BinOp):
        return _collect_aggs(expr.left) + _collect_aggs(expr.right)
    if isinstance(expr, P.NotOp):
        return _collect_aggs(expr.expr)
    return []


def _collect_cols(expr) -> List[P.Col]:
    if isinstance(expr, P.Col):
        return [expr]
    if isinstance(expr, P.BinOp):
        return _collect_cols(expr.left) + _collect_cols(expr.right)
    if isinstance(expr, P.NotOp):
        return _collect_cols(expr.expr)
    return []


def _has_subquery(expr) -> bool:
    if isinstance(expr, P.Subquery):
        return True
    if isinstance(expr, P.BinOp):
        return _has_subquery(expr.left) or _has_subquery(expr.right)
    if isinstance(expr, P.NotOp):
        return _has_subquery(expr.expr)
    return False


def _compile_expr(expr, scope: _Scope):
    """Expr -> fn(flat_cols_tuple) -> array; plus the result dtype."""

    def fn(cols):
        return _eval(expr, scope, cols)

    samples = tuple(jnp.zeros((1,), d) for d in scope.dtypes)
    out_dtype = np.asarray(fn(samples)).dtype
    return fn, out_dtype


def _eval(expr, scope: _Scope, cols):
    if isinstance(expr, P.Lit):
        return jnp.asarray(expr.value)
    if isinstance(expr, P.Col):
        return cols[scope.index_of(expr)]
    if isinstance(expr, P.NotOp):
        return ~_eval(expr.expr, scope, cols)
    if isinstance(expr, P.BinOp):
        a = _eval(expr.left, scope, cols)
        b = _eval(expr.right, scope, cols)
        op = expr.op
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op in ("/", "%"):
            if jnp.issubdtype(jnp.result_type(a, b), jnp.integer):
                # SQL/reference semantics: division truncates toward zero
                # (-7/2 == -3) and % is the matching remainder (-7%2 == -1),
                # so a == (a/b)*b + a%b holds — unlike Python/JAX floored
                # //+%; matches the Average aggregator's truncating reduce
                q = a // b
                r = a - q * b
                q = jnp.where((r != 0) & ((a < 0) != (b < 0)), q + 1, q)
                return q if op == "/" else a - q * b
            return a / b if op == "/" else a % b
        if op == "=":
            return a == b
        if op in ("<>", "!="):
            return a != b
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
        if op == ">":
            return a > b
        if op == ">=":
            return a >= b
        if op == "and":
            return a & b
        if op == "or":
            return a | b
    raise SqlError(f"cannot evaluate {expr}")


class SqlContext:
    """Registry of named streams + the SQL entry point."""

    def __init__(self, circuit: Circuit):
        self.circuit = circuit
        self.tables: Dict[str, Tuple[Stream, List[str]]] = {}

    def register_table(self, name: str, stream: Stream,
                       columns: List[str]) -> None:
        schema = getattr(stream, "schema", None)
        assert schema is not None, "registered streams need schema metadata"
        ncols = len(schema[0]) + len(schema[1])
        assert len(columns) == ncols, (
            f"{name}: {len(columns)} column names for {ncols} columns")
        self.tables[name] = (stream, list(columns))

    # -- planning -----------------------------------------------------------
    def query(self, sql: str) -> Stream:
        return self._plan(P.parse(sql))

    def _plan(self, ast) -> Stream:
        if isinstance(ast, P.SetOp):
            return self._plan_setop(ast)
        return self._plan_select(ast)

    # -- set operations ------------------------------------------------------
    @staticmethod
    def _flatten_rows(stream: Stream, names, tag: str) -> Stream:
        """Normalize to an all-key row layout (set ops compare full rows and
        semijoin/antijoin key on the stream's key columns)."""
        schema = stream.schema
        flat_dts = (*schema[0], *schema[1])
        if not schema[1]:
            out = stream
        else:
            out = stream.map_rows(
                lambda k, v: ((*k, *v), ()), flat_dts, (),
                name=f"sql-rows-{tag}")
            # key-then-val flattening preserves flat column order
            out._sql_nullable_cols = set(
                getattr(stream, "_sql_nullable_cols", ()))
        out._sql_names = list(names)
        return out

    def _plan_setop(self, ast: P.SetOp) -> Stream:
        a = self._plan(ast.left)
        b = self._plan(ast.right)
        a_names = getattr(a, "_sql_names", None) or \
            [f"col{i}" for i in range(len(a.schema[0]) + len(a.schema[1]))]
        na = len(a.schema[0]) + len(a.schema[1])
        nb = len(b.schema[0]) + len(b.schema[1])
        if na != nb:
            raise SqlError(
                f"{ast.op.upper()} operands have {na} vs {nb} columns")
        a = self._flatten_rows(a, a_names, "l")
        b = self._flatten_rows(b, a_names, "r")
        if a.schema[0] != b.schema[0]:
            # promote BOTH sides to the common wider dtypes (casting the
            # right down to the left would wrap values >= 2^31 and create
            # false EXCEPT/INTERSECT equalities)
            dts = tuple(jnp.result_type(x, y)
                        for x, y in zip(a.schema[0], b.schema[0]))

            def cast(s, tag):
                if s.schema[0] == dts:
                    return s
                out = s.map_rows(
                    lambda k, v, _d=dts: (tuple(c.astype(d) for c, d
                                                in zip(k, _d)), ()),
                    dts, (), name=f"sql-setcast-{tag}")
                out._sql_names = list(a_names)
                return out

            a, b = cast(a, "l"), cast(b, "r")
        if ast.op == "union":
            out = a.plus(b)
            out.schema = a.schema
            if not ast.all:
                out = out.distinct()
        elif ast.op == "intersect":
            # set semantics: distinct rows present on BOTH sides (semijoin
            # reduces its right side via keys_distinct internally)
            out = a.distinct().semijoin(b)
        else:  # except
            out = a.distinct().antijoin(b)
        out._sql_names = list(a_names)
        out._sql_nullable_cols = (
            set(getattr(a, "_sql_nullable_cols", ()))
            | set(getattr(b, "_sql_nullable_cols", ())))
        return out

    def _plan_select(self, ast: P.Select) -> Stream:
        stream, scope = self._plan_from(ast)
        if ast.where is not None:
            where = ast.where
            if _has_subquery(where):
                stream, scope, where = self._bind_subqueries(
                    stream, scope, where)
            pred, dt = _compile_expr(where, scope)
            if dt != np.bool_:
                raise SqlError("WHERE must be boolean")
            stream = stream.filter_rows(
                lambda k, v, _p=pred: _p((*k, *v)), name="sql-where")
        has_aggs = any(isinstance(i.expr, P.Agg) for i in ast.items)
        if has_aggs or ast.group_by:
            stream = self._plan_aggregate(ast, stream, scope)
        elif ast.having is not None:
            raise SqlError("HAVING requires GROUP BY / aggregates")
        else:
            stream = self._plan_project(ast, stream, scope)
        if ast.distinct:
            stream = stream.distinct()
        if ast.limit is not None:
            stream = self._plan_topk(ast, stream)
        return stream

    def _source_scope(self, ref: P.Source) -> Tuple[Stream, _Scope]:
        if isinstance(ref, P.SubSource):
            # FROM (query) alias: plan the subquery; its output columns are
            # visible as alias.<name> (base name = last path component)
            sub = self._plan(ref.select)
            schema = sub.schema
            dtypes = [*schema[0], *schema[1]]
            names = getattr(sub, "_sql_names", None) or \
                [f"col{i}" for i in range(len(dtypes))]
            return sub, _Scope(
                [f"{ref.alias}.{n.split('.')[-1]}" for n in names], dtypes,
                nullable=getattr(sub, "_sql_nullable_cols", ()))
        if ref.name not in self.tables:
            raise SqlError(f"unknown table {ref.name}")
        stream, cols = self.tables[ref.name]
        schema = stream.schema
        dtypes = [*schema[0], *schema[1]]
        return stream, _Scope([f"{ref.alias}.{c}" for c in cols], dtypes)

    def _plan_from(self, ast: P.Select) -> Tuple[Stream, _Scope]:
        """Left-deep join chain: fold each JOIN clause onto the accumulated
        (stream, scope) — the reference's Calcite plans multi-way joins the
        same left-deep way before the circuit sees them."""
        left, ls = self._source_scope(ast.table)
        for n, join in enumerate(ast.joins):
            right, rs = self._source_scope(join.table)
            if join.range is not None:
                if join.left:
                    raise SqlError("LEFT JOIN with BETWEEN bounds is not "
                                   "supported yet")
                left, ls = self._fold_range_join(join, left, ls, right, rs,
                                                 n)
            else:
                left, ls = self._fold_equi_join(join, left, ls, right, rs, n)
        return left, ls

    def _fold_equi_join(self, join: P.Join, left, ls, right, rs, n: int):
        lcol, rcol = join.on
        # resolve which side each ON column belongs to
        try:
            li = ls.index_of(lcol)
        except SqlError:
            lcol, rcol = rcol, lcol
            li = ls.index_of(lcol)
        ri = rs.index_of(rcol)
        # promote mixed-dtype ON columns to one key dtype; index_by/map_rows
        # cast their outputs to the declared schema, so both traces carry the
        # same key dtype and lex_probe never truncates probe keys
        key_dt = jnp.result_type(ls.dtypes[li], rs.dtypes[ri])

        def rekey(idx):
            def key_fn(k, v, _i=idx):
                return ((*k, *v)[_i],)

            def val_fn(k, v):
                return tuple((*k, *v))

            return key_fn, val_fn

        lk, lv = rekey(li)
        rk, rv = rekey(ri)
        lkeyed = left.index_by(lk, (key_dt,), val_fn=lv,
                               val_dtypes=tuple(ls.dtypes),
                               name=f"sql-lkey{n}")
        rkeyed = right.index_by(rk, (key_dt,), val_fn=rv,
                                val_dtypes=tuple(rs.dtypes),
                                name=f"sql-rkey{n}")
        joined = lkeyed.join_index(
            rkeyed, lambda k, lvs, rvs: (k, (*lvs, *rvs)),
            (key_dt,), (*ls.dtypes, *rs.dtypes), name=f"sql-join{n}")
        if join.left:
            # LEFT JOIN: unmatched left rows survive, right columns padded
            # with NULL_INT (the dtype's min — documented NULL convention)
            nulls = tuple(NULL_INT(dt) for dt in rs.dtypes)

            def pad(k, v, _nulls=nulls, _dts=tuple(rs.dtypes)):
                return k, (*v, *(jnp.full(v[0].shape, nv, jnp.dtype(dt))
                                 for nv, dt in zip(_nulls, _dts)))

            missing = lkeyed.antijoin(rkeyed).map_rows(
                pad, (key_dt,), (*ls.dtypes, *rs.dtypes),
                name=f"sql-leftpad{n}")
            joined = joined.plus(missing)
            joined.schema = ((key_dt,), (*ls.dtypes, *rs.dtypes))
        rbase = 1 + len(ls.names)
        nullable = {1 + i for i in ls.nullable} | \
            {rbase + i for i in rs.nullable}
        if join.left:
            # every right-side column may now carry the NULL pad
            nullable |= {rbase + i for i in range(len(rs.names))}
        scope = _Scope([f"__jk{n}__", *ls.names, *rs.names],
                       [key_dt, *ls.dtypes, *rs.dtypes], nullable=nullable)
        return joined, scope

    def _fold_range_join(self, join, left, ls, right, rs, n: int):
        """JOIN r ON r.x BETWEEN l.y + c1 AND l.y + c2 -> relative range
        join (operators/join_range.py)."""
        import dbsp_tpu.operators.join_range  # noqa: F401 (register)

        rng = join.range
        try:
            ri = rs.index_of(rng.col)
        except SqlError:
            raise SqlError("range-join column must belong to the joined "
                           f"table: {rng.col}")

        def split_rel(e):
            if isinstance(e, P.Col):
                return e, 0
            if isinstance(e, P.BinOp) and e.op in ("+", "-") and \
                    isinstance(e.left, P.Col) and isinstance(e.right, P.Lit):
                c = int(e.right.value)
                return e.left, c if e.op == "+" else -c
            raise SqlError(
                "range-join bounds must be <left column> [± integer]")

        lo_col, lo_c = split_rel(rng.lo)
        hi_col, hi_c = split_rel(rng.hi)
        if (lo_col.table, lo_col.name) != (hi_col.table, hi_col.name):
            raise SqlError("range-join bounds must share one base column")
        li = ls.index_of(lo_col)
        key_dt = jnp.result_type(ls.dtypes[li], rs.dtypes[ri])

        lkeyed = left.index_by(
            lambda k, v, _i=li: ((*k, *v)[_i],), (key_dt,),
            val_fn=lambda k, v: (*k, *v), val_dtypes=tuple(ls.dtypes),
            name=f"sql-rglkey{n}")
        rkeyed = right.index_by(
            lambda k, v, _i=ri: ((*k, *v)[_i],), (key_dt,),
            val_fn=lambda k, v: (*k, *v), val_dtypes=tuple(rs.dtypes),
            name=f"sql-rgrkey{n}")
        joined = lkeyed.join_range(
            rkeyed, lo_c, hi_c,
            lambda lk, lv, rk, rv: (lk, (*lv, *rv)),
            (key_dt,), (*ls.dtypes, *rs.dtypes), name=f"sql-rangejoin{n}")
        rbase = 1 + len(ls.names)
        scope = _Scope([f"__jk{n}__", *ls.names, *rs.names],
                       [key_dt, *ls.dtypes, *rs.dtypes],
                       nullable={1 + i for i in ls.nullable}
                       | {rbase + i for i in rs.nullable})
        return joined, scope

    # -- scalar subqueries ---------------------------------------------------
    def _bind_subqueries(self, stream, scope, where):
        """Plan each scalar subquery; cross-join its single row into the
        main stream on a unit key; rewrite the WHERE to reference it."""
        subs: List[P.Select] = []

        def rewrite(e):
            if isinstance(e, P.Subquery):
                subs.append(e.select)
                return P.Col(None, f"__sub{len(subs) - 1}__")
            if isinstance(e, P.BinOp):
                return P.BinOp(e.op, rewrite(e.left), rewrite(e.right))
            if isinstance(e, P.NotOp):
                return P.NotOp(rewrite(e.expr))
            return e

        where2 = rewrite(where)
        flat_dts = list(scope.dtypes)
        names = list(scope.names)
        for i, sel in enumerate(subs):
            sub = self._plan(sel)
            sschema = sub.schema
            scols = (*sschema[0], *sschema[1])
            if len(scols) != 1:
                raise SqlError("scalar subquery must select one column")
            unit = lambda k, v: (jnp.zeros_like((*k, *v)[0]).astype(jnp.int64),)  # noqa: E731,E501
            main_ck = stream.index_by(
                unit, (jnp.int64,), val_fn=lambda k, v: (*k, *v),
                val_dtypes=tuple(flat_dts), name=f"sql-crossL{i}")
            sub_ck = sub.index_by(
                unit, (jnp.int64,), val_fn=lambda k, v: (*k, *v),
                val_dtypes=scols, name=f"sql-crossR{i}")
            stream = main_ck.join_index(
                sub_ck, lambda k, mv, sv: (k, (*mv, *sv)),
                (jnp.int64,), (*flat_dts, *scols), name=f"sql-cross{i}")
            names = [f"__cross{i}__", *names, f"__sub{i}__"]
            flat_dts = [jnp.int64, *flat_dts, scols[0]]
        return stream, _Scope(names, flat_dts), where2

    def _plan_project(self, ast: P.Select, stream: Stream, scope: _Scope
                      ) -> Stream:
        if len(ast.items) == 1 and isinstance(ast.items[0].expr, P.Col) \
                and ast.items[0].expr.name == "*":
            # internal plumbing columns (join keys, cross-join units,
            # subquery scalars — all dunder-named) are not user-visible
            visible = [i for i, n in enumerate(scope.names)
                       if not (n.startswith("__") and n.endswith("__"))]
            if len(visible) == len(scope.names):
                stream._sql_names = list(scope.names)
                stream._sql_nullable_cols = set(scope.nullable)
                return stream
            out = stream.map_rows(
                lambda k, v, _i=tuple(visible): (
                    tuple((*k, *v)[i] for i in _i), ()),
                tuple(scope.dtypes[i] for i in visible), (),
                name="sql-star")
            out._sql_names = [scope.names[i] for i in visible]
            out._sql_nullable_cols = {j for j, i in enumerate(visible)
                                      if i in scope.nullable}
            return out
        fns, dts = [], []
        for item in ast.items:
            fn, dt = _compile_expr(item.expr, scope)
            fns.append(fn)
            dts.append(dt)

        def project(k, v):
            cols = (*k, *v)
            outs = tuple(jnp.broadcast_to(f(cols), cols[0].shape)
                         for f in fns)
            return outs, ()

        out = stream.map_rows(project, tuple(dts), (), name="sql-project")
        out._sql_names = _item_names(ast.items)
        # an output column may be NULL if its expression references any
        # nullable column (for bare columns this is exact; for arithmetic
        # the value is transformed but downstream must still be wary)
        out._sql_nullable_cols = {
            j for j, item in enumerate(ast.items)
            if any(scope.index_of(c) in scope.nullable
                   for c in _collect_cols(item.expr))}
        return out

    def _plan_aggregate(self, ast: P.Select, stream: Stream, scope: _Scope
                        ) -> Stream:
        group_idx = [scope.index_of(c) for c in ast.group_by]
        key_dts = [scope.dtypes[i] for i in group_idx] or [jnp.int64]

        aggs: List[Tuple[int, P.Agg]] = []
        for pos, item in enumerate(ast.items):
            if isinstance(item.expr, P.Agg):
                aggs.append((pos, item.expr))
            elif isinstance(item.expr, P.Col):
                if scope.index_of(item.expr) not in group_idx:
                    raise SqlError(
                        f"{item.expr} must appear in GROUP BY or an aggregate")
            else:
                raise SqlError("non-aggregate select items must be columns")
        # aggregates referenced only by HAVING are computed but not projected
        having_aggs = _collect_aggs(ast.having) if ast.having else []
        selected = [a for _, a in aggs]
        for ha in having_aggs:
            if ha not in selected:
                aggs.append((None, ha))
                selected.append(ha)

        def _null_refs(agg: P.Agg):
            """Scope indices of NULLABLE columns the agg arg references."""
            if agg.arg is None:
                return []
            return [i for i in (scope.index_of(c)
                                for c in _collect_cols(agg.arg))
                    if i in scope.nullable]

        def keyed_stream(agg: P.Agg) -> Stream:
            if agg.arg is None:
                arg_fn, arg_dt = (lambda cols: jnp.ones_like(cols[0])), \
                    np.dtype(np.int64)
            else:
                arg_fn, arg_dt = _compile_expr(agg.arg, scope)
            nrefs = tuple(_null_refs(agg))

            def mapper(k, v, _f=arg_fn, _n=nrefs, _dt=arg_dt):
                cols = (*k, *v)
                keys = tuple(cols[i] for i in group_idx) or \
                    (jnp.zeros_like(cols[0]),)
                out = jnp.broadcast_to(_f(cols), cols[0].shape)
                if _n:
                    # SQL NULL propagation: an expression over a NULL input
                    # is NULL — re-mark rows whose referenced nullable cols
                    # carry the pad BEFORE arithmetic transformed it
                    isnull = jnp.zeros(cols[0].shape, jnp.bool_)
                    for i in _n:
                        isnull = isnull | (
                            cols[i] == NULL_INT(scope.dtypes[i]))
                    out = jnp.where(isnull,
                                    jnp.asarray(NULL_INT(_dt),
                                                jnp.dtype(_dt)), out)
                return keys, (out,)

            return stream.map_rows(mapper, tuple(key_dts), (arg_dt,),
                                   name="sql-keyed")

        # an aggregate is NULL-aware iff its argument references a column
        # an outer join could have padded (SQL semantics: aggregates skip
        # NULLs; all-NULL groups aggregate to NULL). Everything else keeps
        # the linear fast path.
        results = []
        for pos, agg in aggs:
            ks = keyed_stream(agg)
            if _null_refs(agg):
                inst = _SqlNullAgg(agg.fn)
            else:
                cls = AGG_CLASSES[agg.fn]
                inst = cls() if agg.fn == "count" else cls(0)
            results.append(ks.aggregate(inst, name=f"sql-{agg.fn}"))
        combined = results[0]
        for extra in results[1:]:
            n = len(combined.schema[1])
            combined = combined.join_index(
                extra, lambda k, a, b: (k, (*a, *b)),
                tuple(key_dts),
                (*combined.schema[1], *extra.schema[1]), name="sql-aggjoin")

        if ast.having is not None:
            # evaluate the HAVING predicate over (group keys, agg columns):
            # rewrite Agg nodes to their slot in combined's value columns
            # and group columns to their key slot
            hscope = _Scope(
                [f"__g{i}__" for i in range(len(group_idx))] +
                [f"__a{j}__" for j in range(len(aggs))],
                [*key_dts, *([jnp.int64] * len(aggs))])

            def hrewrite(e):
                if isinstance(e, P.Agg):
                    return P.Col(None, f"__a{selected.index(e)}__")
                if isinstance(e, P.Col):
                    gi = group_idx.index(scope.index_of(e))
                    return P.Col(None, f"__g{gi}__")
                if isinstance(e, P.BinOp):
                    return P.BinOp(e.op, hrewrite(e.left), hrewrite(e.right))
                if isinstance(e, P.NotOp):
                    return P.NotOp(hrewrite(e.expr))
                return e

            pred, dt = _compile_expr(hrewrite(ast.having), hscope)
            if dt != np.bool_:
                raise SqlError("HAVING must be boolean")
            combined = combined.filter_rows(
                lambda k, v, _p=pred: _p((*k, *v)), name="sql-having")

        # order output columns as selected: group cols come from the key
        agg_positions = {pos: i for i, (pos, _) in enumerate(aggs)
                         if pos is not None}

        def finalize(k, v):
            outs = []
            for pos, item in enumerate(ast.items):
                if pos in agg_positions:
                    outs.append(v[agg_positions[pos]])
                else:
                    outs.append(k[group_idx.index(
                        scope.index_of(item.expr))])
            return tuple(outs), ()

        out_dts = []
        for pos, item in enumerate(ast.items):
            if pos in agg_positions:
                out_dts.append(jnp.int64)
            else:
                out_dts.append(scope.dtypes[scope.index_of(item.expr)])
        out = combined.map_rows(finalize, tuple(out_dts), (),
                                name="sql-finalize")
        out._sql_names = _item_names(ast.items)
        # NULL-aware aggregates can emit NULL (all-NULL groups); group
        # columns inherit their source column's nullability
        out._sql_nullable_cols = {
            pos for pos, item in enumerate(ast.items)
            if (pos in agg_positions and isinstance(item.expr, P.Agg)
                and _null_refs(item.expr))
            or (pos not in agg_positions
                and scope.index_of(item.expr) in scope.nullable)}
        return out

    def _plan_topk(self, ast: P.Select, stream: Stream) -> Stream:
        """ORDER BY ... LIMIT n -> global top-K: re-key to a unit key with
        the order columns leading the value tuple, take K, restore layout."""
        names = getattr(stream, "_sql_names", None)
        schema = stream.schema
        flat_dts = [*schema[0], *schema[1]]
        if names is None:
            names = [f"col{i}" for i in range(len(flat_dts))]
        aux = _Scope(names, flat_dts)
        order_idx = [aux.index_of(o.col) for o in ast.order_by]
        descs = {o.desc for o in ast.order_by}
        if len(descs) > 1:
            raise SqlError("mixed ASC/DESC ORDER BY is not supported yet")
        desc = descs.pop() if descs else False
        rest = [i for i in range(len(flat_dts)) if i not in order_idx]
        perm = [*order_idx, *rest]
        inv = [perm.index(i) for i in range(len(flat_dts))]

        keyed = stream.index_by(
            lambda k, v: (jnp.zeros_like((*k, *v)[0]).astype(jnp.int64),),
            (jnp.int64,),
            val_fn=lambda k, v, _p=tuple(perm): tuple((*k, *v)[i]
                                                      for i in _p),
            val_dtypes=tuple(flat_dts[i] for i in perm), name="sql-orderkey")
        top = keyed.topk(ast.limit, largest=desc, name="sql-limit")
        out = top.map_rows(
            lambda k, v, _i=tuple(inv): (tuple(v[i] for i in _i), ()),
            tuple(flat_dts), (), name="sql-unorder")
        out._sql_names = names
        return out
