"""SQL planner: lowers parsed SELECTs onto incremental circuit operators.

The in-tree stand-in for the reference's out-of-tree Calcite->Rust compiler
(``pipeline_manager/src/compiler.rs`` invokes it as a subprocess; SURVEY.md
L5): here SQL plans straight into the same Stream operators hand-built
queries use, so every registered view is incrementally maintained — inserts
and retractions on base tables propagate deltas through WHERE/JOIN/GROUP BY.

Lowering map:
    WHERE                -> filter_rows (columnar predicate)
    JOIN ... ON a = b    -> index_by + incremental bilinear join
    LEFT JOIN            -> inner join ∪ (antijoined left rows padded with
                            the NULL marker, iinfo.min — see NULL_INT)
    JOIN ON r BETWEEN l+c1 AND l+c2 -> incremental relative range join
                            (operators/join_range.py)
    GROUP BY + agg       -> index_by + incremental aggregate (one per agg,
                            joined on the group key — reference's multi-agg
                            plans share the same shape)
    HAVING               -> filter over the joined aggregate columns
    DISTINCT             -> incremental distinct
    ORDER BY ... LIMIT n -> global top-K (operators/topk.py); ORDER BY
                            without LIMIT is a no-op (Z-sets are unordered)
    scalar subqueries    -> planned standalone, cross-joined on a unit key,
                            then referenced like columns in WHERE
    plain SELECT         -> map_rows projection
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dbsp_tpu.circuit.builder import Circuit, Stream
from dbsp_tpu.operators.aggregate import Max, Min
from dbsp_tpu.operators.aggregate_linear import (
    LinearAverage as Average, LinearCount as Count, LinearSum as Sum)
from dbsp_tpu.sql import parser as P

AGG_CLASSES = {"count": Count, "sum": Sum, "min": Min, "max": Max,
               "avg": Average}


@dataclasses.dataclass(frozen=True)
class _SqlNullAgg:
    """NULL-aware SQL aggregation for nullable (outer-joined) columns:
    rows whose argument carries the NULL_INT marker are ignored, and a
    group with no non-NULL rows aggregates to NULL (count: to 0) — SQL
    semantics sqlite also implements. Only used when the query has a LEFT
    JOIN (other queries keep the linear fast path).

    The reduction itself is NOT a third copy of the segment_sum glue: the
    NULL mask zeroes the weights, then the op and the non-NULL count ride
    ONE :func:`dbsp_tpu.operators.aggregate.segment_reduce` call — the
    same five-op dispatch (native ``ZsetSegmentReduceFfi`` on CPU) every
    built-in :class:`~dbsp_tpu.operators.aggregate.Aggregator` lowers
    through — with only the aggregate-to-NULL fixup as an elementwise
    tail. ``reduce_spec`` stays ``None``: the NULL mask and the fixup are
    not expressible as a bare spec, so the fused aggregate megakernel
    skips these (they only occur on LEFT JOIN plans)."""

    fn: str = "sum"
    out_dtypes = (jnp.int64,)
    insert_combinable = False

    @property
    def name(self):
        return f"sql-null-{self.fn}"

    def reduce_spec(self):
        return None

    def reduce(self, val_cols, weights, seg, num_segments):
        from dbsp_tpu.operators.aggregate import segment_reduce

        v = val_cols[0]
        null = NULL_INT(v.dtype)
        w = jnp.where(v == null, 0, weights)
        if self.fn == "count":
            # COUNT of all-NULL is 0, not NULL
            return segment_reduce((("count", 0),), (v,), w, seg,
                                  num_segments)
        out, cnt = segment_reduce(((self.fn, 0), ("count", 0)), (v,), w,
                                  seg, num_segments)
        return (jnp.where(cnt > 0, out, jnp.asarray(null, out.dtype)),)

# SQL NULL marker for outer-join padding: the dtype's MINIMUM (the maximum
# is the engine's dead-row sentinel). Documented engine-wide convention —
# the reference's nullable columns become (value | NULL_INT) here.
def NULL_INT(dtype):
    return int(np.iinfo(np.dtype(dtype)).min)


class SqlError(ValueError):
    pass


def _like_regex(pattern: str):
    """Compile a SQL LIKE pattern (% = any run, _ = any one char)."""
    import re

    return re.compile("^" + "".join(
        ".*" if ch == "%" else "." if ch == "_" else re.escape(ch)
        for ch in pattern) + "$")


class SqlStrings:
    """Append-only string dictionary shared by every string column of one
    SqlContext (the engine-wide VARCHAR design: variable-length text is
    dictionary-encoded on the host, fixed-width int64 codes flow on device —
    the same scheme Nexmark q21/q22 use in ``nexmark/strings.py``, promoted
    to a planner type). Codes carry EQUALITY only (=, <>, IN, GROUP BY,
    JOIN); ordering comparisons over strings are rejected at plan time
    because code order is arrival order. LIKE snapshots the dictionary at
    trace time into a code set (exact for data registered before planning;
    a stream that first introduces a string AFTER a LIKE was planned needs
    a replan — documented limitation)."""

    def __init__(self):
        self._codes: Dict[str, int] = {}
        self._strs: List[str] = []
        # (pattern, compiled regex, dictionary length) per planned LIKE:
        # the code set a LIKE lowered to is exact only for this prefix of
        # the dictionary — growth past it is checked in encode()
        self._like_plans: List[Tuple[str, object, int]] = []

    def encode(self, s: str) -> int:
        code = self._codes.get(s)
        if code is None:
            # Dictionary-growth hazard (ADVICE r5): a planned LIKE matches
            # a code set snapshotted at trace time, so a string first seen
            # AFTER the trace can never enter that set. Growth is harmless
            # while the new string matches no planned pattern (its absence
            # from the hit set is the correct answer, for NOT LIKE too);
            # a string that WOULD match must refuse ingestion instead of
            # silently dropping rows from the maintained view.
            for pattern, rx, snap in self._like_plans:
                if rx.match(s):
                    raise SqlError(
                        f"string dictionary grew past a planned LIKE: "
                        f"{s!r} matches pattern {pattern!r}, which was "
                        f"lowered against the dictionary at {snap} "
                        f"entries ({len(self._strs)} now) and can never "
                        "match codes minted later — the view would "
                        "silently miss these rows. Register the full "
                        "string domain before planning, or re-plan the "
                        "LIKE views (rebuild the SqlContext and call "
                        "replanned_like()) after new strings arrive.")
            code = len(self._strs)
            self._codes[s] = code
            self._strs.append(s)
        return code

    def like_planned(self, pattern: str) -> None:
        """Record that a LIKE over ``pattern`` was traced against the
        CURRENT dictionary — encode() henceforth rejects new strings that
        the planned filter would wrongly never match. A retrace of the
        same pattern refreshes its snapshot in place (between two traces
        no matching string can have been minted — it would have raised)."""
        entry = (pattern, _like_regex(pattern), len(self._strs))
        for i, (p, _, _) in enumerate(self._like_plans):
            if p == pattern:
                self._like_plans[i] = entry
                return
        self._like_plans.append(entry)

    def replanned_like(self) -> None:
        """Drop the LIKE snapshots after the owner re-planned every LIKE
        view (re-tracing re-snapshots the dictionary via like_planned)."""
        self._like_plans.clear()

    def decode(self, code: int) -> Optional[str]:
        if code == NULL_INT(np.int64) or code < 0 or \
                code >= len(self._strs):
            return None
        return self._strs[int(code)]

    def like_codes(self, pattern: str) -> List[int]:
        """Codes of all known strings matching a SQL LIKE pattern
        (% = any run, _ = any one char)."""
        rx = _like_regex(pattern)
        return [i for i, s in enumerate(self._strs) if rx.match(s)]


class _Scope:
    """Column-name resolution over a stream's (key+val) columns.

    ``nullable`` holds the indices of columns that may carry the NULL_INT
    marker (outer-join padding) — NULL-awareness in predicates,
    projections, and aggregates keys off it. ``strs`` holds the indices of
    dictionary-encoded string columns; ``strings`` is the owning
    dictionary. All three propagate through joins, subqueries, set ops."""

    def __init__(self, names: List[str], dtypes: List, nullable=(),
                 strs=(), strings: Optional[SqlStrings] = None):
        self.names = list(names)
        self.dtypes = list(dtypes)
        self.nullable = frozenset(nullable)
        self.strs = frozenset(strs)
        self.strings = strings

    def index_of(self, col: P.Col) -> int:
        want = f"{col.table}.{col.name}" if col.table else col.name
        hits = [i for i, n in enumerate(self.names)
                if n == want or (col.table is None and
                                 n.split(".")[-1] == col.name)]
        if not hits:
            raise SqlError(f"unknown column {want}; have {self.names}")
        if len(hits) > 1:
            raise SqlError(f"ambiguous column {want}")
        return hits[0]


def _item_names(items) -> List[str]:
    out = []
    for i, item in enumerate(items):
        if item.alias:
            out.append(item.alias)
        elif isinstance(item.expr, P.Col):
            out.append(f"{item.expr.table}.{item.expr.name}"
                       if item.expr.table else item.expr.name)
        else:
            out.append(f"col{i}")
    return out


def _collect_aggs(expr) -> List[P.Agg]:
    if isinstance(expr, P.Agg):
        return [expr]
    if isinstance(expr, P.BinOp):
        return _collect_aggs(expr.left) + _collect_aggs(expr.right)
    if isinstance(expr, P.NotOp):
        return _collect_aggs(expr.expr)
    if isinstance(expr, (P.IsNull, P.InList, P.LikeOp)):
        return _collect_aggs(expr.expr)
    return []


def _collect_cols(expr) -> List[P.Col]:
    if isinstance(expr, P.Col):
        return [expr]
    if isinstance(expr, P.BinOp):
        return _collect_cols(expr.left) + _collect_cols(expr.right)
    if isinstance(expr, P.NotOp):
        return _collect_cols(expr.expr)
    if isinstance(expr, (P.IsNull, P.InList, P.LikeOp)):
        return _collect_cols(expr.expr)
    return []


def _has_subquery(expr) -> bool:
    if isinstance(expr, P.Subquery):
        return True
    if isinstance(expr, P.BinOp):
        return _has_subquery(expr.left) or _has_subquery(expr.right)
    if isinstance(expr, P.NotOp):
        return _has_subquery(expr.expr)
    if isinstance(expr, (P.IsNull, P.InList, P.LikeOp)):
        return _has_subquery(expr.expr)
    return False


def _split_conjuncts(where):
    """Split a WHERE AND-tree into (plain predicate | None, membership
    conjuncts). Membership = IN (SELECT) / EXISTS, possibly NOT-wrapped
    (normalized onto the node's ``negated`` flag). Membership predicates
    under OR are rejected — they lower onto joins, which can't be unioned
    row-wise with a scalar predicate."""
    plain: List = []
    members: List = []

    def walk(e):
        if isinstance(e, P.BinOp) and e.op == "and":
            walk(e.left)
            walk(e.right)
            return
        if isinstance(e, P.NotOp) and isinstance(
                e.expr, (P.InSubquery, P.ExistsOp)):
            inner = e.expr
            members.append(dataclasses.replace(
                inner, negated=not inner.negated))
            return
        if isinstance(e, (P.InSubquery, P.ExistsOp)):
            members.append(e)
            return
        if _contains_membership(e):
            raise SqlError(
                "IN (SELECT)/EXISTS must be AND-level conjuncts (OR over "
                "set membership is not supported)")
        plain.append(e)

    walk(where)
    pred = None
    for e in plain:
        pred = e if pred is None else P.BinOp("and", pred, e)
    return pred, members


def _contains_membership(e) -> bool:
    if isinstance(e, (P.InSubquery, P.ExistsOp)):
        return True
    if isinstance(e, P.BinOp):
        return _contains_membership(e.left) or _contains_membership(e.right)
    if isinstance(e, P.NotOp):
        return _contains_membership(e.expr)
    return False


@dataclasses.dataclass
class _V:
    """A three-valued expression result: raw ``val``, a boolean NULL mask
    (None == statically never NULL — rows where the mask is True carry
    garbage in ``val``), and whether the expression is string-typed."""

    val: object
    null: object = None          # None | bool array
    is_str: bool = False

    def nullm(self, shape):
        return jnp.zeros(shape, jnp.bool_) if self.null is None else \
            jnp.broadcast_to(self.null, shape)


def _or_null(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a | b


def _compile_pred(expr, scope: _Scope):
    """Predicate compiler: fn(flat_cols) -> keep mask. SQL WHERE/HAVING
    keep rows where the predicate is TRUE — NULL counts as not-kept
    (three-valued logic collapses at the filter boundary)."""
    samples = tuple(jnp.zeros((1,), d) for d in scope.dtypes)
    probe = _eval3(expr, scope, samples)
    if np.asarray(probe.val).dtype != np.bool_:
        raise SqlError("predicate must be boolean")

    def fn(cols):
        v = _eval3(expr, scope, cols)
        keep = jnp.broadcast_to(v.val, cols[0].shape)
        if v.null is not None:
            keep = keep & ~jnp.broadcast_to(v.null, cols[0].shape)
        return keep

    return fn


def _compile_proj(expr, scope: _Scope):
    """Projection compiler: fn(flat_cols) -> value column with NULL rows
    re-marked as NULL_INT. Returns (fn, dtype, nullable, is_str)."""
    samples = tuple(jnp.zeros((1,), d) for d in scope.dtypes)
    probe = _eval3(expr, scope, samples)
    dt = np.asarray(probe.val).dtype
    if dt == np.bool_:
        dt = np.dtype(np.int64)  # SQL exposes booleans as 0/1 integers
    nullable = probe.null is not None

    def fn(cols):
        v = _eval3(expr, scope, cols)
        out = jnp.broadcast_to(v.val, cols[0].shape).astype(dt)
        if v.null is not None:
            out = jnp.where(jnp.broadcast_to(v.null, cols[0].shape),
                            jnp.asarray(NULL_INT(dt), dt), out)
        return out

    return fn, dt, nullable, probe.is_str


def _compile_expr(expr, scope: _Scope):
    """Legacy two-valued entry (non-null scopes): fn + dtype."""
    fn, dt, _, _ = _compile_proj(expr, scope)
    return fn, dt


def _eval3(expr, scope: _Scope, cols) -> _V:
    """Three-valued SQL evaluation (sqlite semantics): any arithmetic or
    comparison over NULL is NULL; AND/OR/NOT follow Kleene logic; IS NULL /
    IN / LIKE / EXISTS produce their SQL results. Rows whose mask says NULL
    carry garbage values — every consumer masks before acting."""
    if isinstance(expr, P.Lit):
        if expr.value is None:
            return _V(jnp.asarray(0, jnp.int64), jnp.asarray(True))
        if isinstance(expr.value, str):
            if scope.strings is None:
                raise SqlError("string literal but no string dictionary "
                               "registered")
            return _V(jnp.asarray(scope.strings.encode(expr.value),
                                  jnp.int64), None, True)
        return _V(jnp.asarray(expr.value))
    if isinstance(expr, P.Col):
        i = scope.index_of(expr)
        c = cols[i]
        null = (c == NULL_INT(scope.dtypes[i])) if i in scope.nullable \
            else None
        return _V(c, null, i in scope.strs)
    if isinstance(expr, P.NotOp):
        v = _eval3(expr.expr, scope, cols)
        return _V(~v.val, v.null)
    if isinstance(expr, P.IsNull):
        v = _eval3(expr.expr, scope, cols)
        isnull = v.null if v.null is not None else jnp.asarray(False)
        return _V(~isnull if expr.negated else isnull, None)
    if isinstance(expr, P.InList):
        v = _eval3(expr.expr, scope, cols)
        has_null_lit = any(lit.value is None for lit in expr.values)
        lits = [lit for lit in expr.values if lit.value is not None]
        if v.is_str and not all(isinstance(lit.value, str) for lit in lits):
            raise SqlError("IN list over a string column needs string "
                           "literals")
        codes = [_eval3(lit, scope, cols).val for lit in lits]
        hit = jnp.asarray(False)
        for c in codes:
            hit = hit | (v.val == c)
        # x IN (..., NULL): no match collapses to NULL, not FALSE
        null = v.null
        if has_null_lit:
            null = _or_null(null, ~hit)
        return _V(~hit if expr.negated else hit, null)
    if isinstance(expr, P.LikeOp):
        v = _eval3(expr.expr, scope, cols)
        if not v.is_str:
            raise SqlError("LIKE requires a string expression")
        codes = scope.strings.like_codes(expr.pattern)
        # Snapshot the dictionary when the filter KERNEL traces (cols are
        # tracers) — from then on encode() of a new matching string raises
        # instead of silently missing this filter (see SqlStrings). The
        # plan-time type probe (eager sample columns) is not a snapshot:
        # its code set is discarded and re-derived at trace time.
        if isinstance(v.val, jax.core.Tracer):
            scope.strings.like_planned(expr.pattern)
        hit = jnp.asarray(False)
        for c in codes:
            hit = hit | (v.val == c)
        hit = jnp.broadcast_to(hit, jnp.shape(v.val))
        return _V(~hit if expr.negated else hit, v.null)
    if isinstance(expr, P.BinOp):
        a = _eval3(expr.left, scope, cols)
        b = _eval3(expr.right, scope, cols)
        op = expr.op
        if a.is_str != b.is_str:
            raise SqlError(f"cannot compare string and number with {op}")
        if a.is_str and op not in ("=", "<>", "!="):
            raise SqlError(f"operator {op} is not defined over strings "
                           "(dictionary codes carry equality only)")
        null = _or_null(a.null, b.null)
        av, bv = a.val, b.val
        if op in ("+", "-", "*"):
            val = av + bv if op == "+" else \
                av - bv if op == "-" else av * bv
            return _V(val, null)
        if op in ("/", "%"):
            if jnp.issubdtype(jnp.result_type(av, bv), jnp.integer):
                # SQL/reference semantics: division truncates toward zero
                # (-7/2 == -3) and % is the matching remainder (-7%2 == -1),
                # so a == (a/b)*b + a%b holds — unlike Python/JAX floored
                # //+%; matches the Average aggregator's truncating reduce.
                # NULL-masked rows may carry zero divisors: divide by a
                # safe stand-in there (the result is garbage behind the
                # mask either way; this keeps the kernel trap-free).
                shape = jnp.shape(av + bv)
                divnull = _or_null(null, jnp.broadcast_to(bv == 0, shape))
                safe = jnp.where(jnp.broadcast_to(bv == 0, shape),
                                 jnp.ones_like(bv), bv)
                q = av // safe
                r = av - q * safe
                q = jnp.where((r != 0) & ((av < 0) != (safe < 0)), q + 1, q)
                val = q if op == "/" else av - q * safe
                return _V(val, divnull)
            return _V(av / bv if op == "/" else av % bv, null)
        cmps = {"=": lambda: av == bv,
                "<>": lambda: av != bv, "!=": lambda: av != bv,
                "<": lambda: av < bv, "<=": lambda: av <= bv,
                ">": lambda: av > bv, ">=": lambda: av >= bv}
        if op in cmps:
            return _V(cmps[op](), null)
        if op in ("and", "or"):
            shape = jnp.shape(a.val & b.val)
            an = a.nullm(shape)
            bn = b.nullm(shape)
            av = jnp.broadcast_to(a.val, shape)
            bv = jnp.broadcast_to(b.val, shape)
            if op == "and":
                # Kleene: FALSE dominates NULL
                known_f = (~an & ~av) | (~bn & ~bv)
                return _V(av & bv & ~an & ~bn, (an | bn) & ~known_f)
            known_t = (~an & av) | (~bn & bv)
            return _V((av & ~an) | (bv & ~bn),
                      (an | bn) & ~known_t)
    raise SqlError(f"cannot evaluate {expr}")


class SqlContext:
    """Registry of named streams + the SQL entry point."""

    def __init__(self, circuit: Circuit):
        self.circuit = circuit
        self.tables: Dict[str, Tuple[Stream, List[str], frozenset]] = {}
        # one dictionary per context: every string column of every table
        # shares it, so equality/joins across tables compare codes directly
        self.strings = SqlStrings()

    def register_table(self, name: str, stream: Stream,
                       columns: List[str],
                       string_cols: Tuple[str, ...] = (),
                       nullable_cols: Tuple[str, ...] = ()) -> None:
        """``string_cols`` names the dictionary-encoded VARCHAR columns —
        their device representation is int64 codes from ``self.strings``
        (encode rows with :meth:`encode_row` before pushing them).
        ``nullable_cols`` declares columns whose rows may carry SQL NULL
        (the NULL_INT marker): predicates/projections/aggregates over them
        run the three-valued path. Columns default to NOT NULL — the
        planner then keeps the cheaper two-valued kernels and linear
        aggregates (the inverse of SQL DDL's default, chosen so hot
        streams don't pay for nullability they never use)."""
        from dbsp_tpu.operators.registry import require_schema

        schema = require_schema(stream, f"register_table({name!r})")
        ncols = len(schema[0]) + len(schema[1])
        if len(columns) != ncols:
            raise ValueError(
                f"{name}: {len(columns)} column names for {ncols} columns")
        for label, sel in (("string_cols", string_cols),
                           ("nullable_cols", nullable_cols)):
            unknown = set(sel) - set(columns)
            assert not unknown, f"{name}: {label} {unknown} not in columns"
        self.tables[name] = (stream, list(columns),
                             frozenset(columns.index(c)
                                       for c in string_cols),
                             frozenset(columns.index(c)
                                       for c in nullable_cols))

    def encode_row(self, table: str, row) -> tuple:
        """Encode a host row's string cells (str -> code, None -> NULL).
        NULL markers are per-column-dtype (int32 NULL is int32's min)."""
        stream, cols, strs, _ = self.tables[table]
        schema = stream.schema
        dts = [*schema[0], *schema[1]]
        out = []
        for i, cell in enumerate(row):
            if i in strs:
                out.append(NULL_INT(dts[i]) if cell is None
                           else self.strings.encode(cell))
            elif cell is None:
                out.append(NULL_INT(dts[i]))
            else:
                out.append(cell)
        return tuple(out)

    def decode_output(self, stream: Stream, rows: Dict) -> Dict:
        """Decode a result ``to_dict()``: string codes back to text, NULL
        markers to None — the serving-boundary inverse of encode_row."""
        strs = getattr(stream, "_sql_str_cols", set())
        nullable = getattr(stream, "_sql_nullable_cols", set())
        schema = getattr(stream, "schema", ((), ()))
        flat_dts = [*schema[0], *schema[1]]
        nulls = [NULL_INT(d) if i < len(flat_dts) else NULL_INT(np.int64)
                 for i, d in enumerate(flat_dts)]
        out: Dict = {}
        for row, w in rows.items():
            cells = []
            for i, cell in enumerate(row):
                if i in strs:
                    cells.append(self.strings.decode(cell))
                elif i in nullable and i < len(nulls) and cell == nulls[i]:
                    cells.append(None)
                else:
                    cells.append(cell)
            out[tuple(cells)] = w
        return out

    # -- planning -----------------------------------------------------------
    def query(self, sql: str) -> Stream:
        return self._plan(P.parse(sql))

    def _plan(self, ast) -> Stream:
        if isinstance(ast, P.SetOp):
            return self._plan_setop(ast)
        return self._plan_select(ast)

    # -- set operations ------------------------------------------------------
    @staticmethod
    def _flatten_rows(stream: Stream, names, tag: str) -> Stream:
        """Normalize to an all-key row layout (set ops compare full rows and
        semijoin/antijoin key on the stream's key columns)."""
        schema = stream.schema
        flat_dts = (*schema[0], *schema[1])
        if not schema[1]:
            out = stream
        else:
            out = stream.map_rows(
                lambda k, v: ((*k, *v), ()), flat_dts, (),
                name=f"sql-rows-{tag}")
            # key-then-val flattening preserves flat column order
            out._sql_nullable_cols = set(
                getattr(stream, "_sql_nullable_cols", ()))
            out._sql_str_cols = set(getattr(stream, "_sql_str_cols", ()))
        out._sql_names = list(names)
        return out

    def _plan_setop(self, ast: P.SetOp) -> Stream:
        a = self._plan(ast.left)
        b = self._plan(ast.right)
        a_names = getattr(a, "_sql_names", None) or \
            [f"col{i}" for i in range(len(a.schema[0]) + len(a.schema[1]))]
        na = len(a.schema[0]) + len(a.schema[1])
        nb = len(b.schema[0]) + len(b.schema[1])
        if na != nb:
            raise SqlError(
                f"{ast.op.upper()} operands have {na} vs {nb} columns")
        a = self._flatten_rows(a, a_names, "l")
        b = self._flatten_rows(b, a_names, "r")
        if a.schema[0] != b.schema[0]:
            # promote BOTH sides to the common wider dtypes (casting the
            # right down to the left would wrap values >= 2^31 and create
            # false EXCEPT/INTERSECT equalities)
            dts = tuple(jnp.result_type(x, y)
                        for x, y in zip(a.schema[0], b.schema[0]))

            def cast(s, tag):
                if s.schema[0] == dts:
                    return s
                out = s.map_rows(
                    lambda k, v, _d=dts: (tuple(c.astype(d) for c, d
                                                in zip(k, _d)), ()),
                    dts, (), name=f"sql-setcast-{tag}")
                out._sql_names = list(a_names)
                return out

            a, b = cast(a, "l"), cast(b, "r")
        if ast.op == "union":
            out = a.plus(b)
            out.schema = a.schema
            if not ast.all:
                out = out.distinct()
        elif ast.op == "intersect":
            # set semantics: distinct rows present on BOTH sides (semijoin
            # reduces its right side via keys_distinct internally)
            out = a.distinct().semijoin(b)
        else:  # except
            out = a.distinct().antijoin(b)
        out._sql_names = list(a_names)
        out._sql_nullable_cols = (
            set(getattr(a, "_sql_nullable_cols", ()))
            | set(getattr(b, "_sql_nullable_cols", ())))
        sa = set(getattr(a, "_sql_str_cols", ()))
        sb = set(getattr(b, "_sql_str_cols", ()))
        if sa != sb:
            raise SqlError(f"{ast.op.upper()}: string/number column "
                           "positions differ between operands")
        out._sql_str_cols = sa
        return out

    def _plan_select(self, ast: P.Select) -> Stream:
        stream, scope = self._plan_from(ast)
        if ast.where is not None:
            where = ast.where
            if _has_subquery(where):
                stream, scope, where = self._bind_subqueries(
                    stream, scope, where)
            # split the AND-tree: IN (SELECT)/EXISTS conjuncts lower onto
            # semijoin/antijoin (facade: the reference compiles these to
            # the same delta-set operators); the rest stays one predicate
            plain, memberships = _split_conjuncts(where)
            for m in memberships:
                stream = self._lower_membership(m, stream, scope)
            if plain is not None:
                pred = _compile_pred(plain, scope)
                stream = stream.filter_rows(
                    lambda k, v, _p=pred: _p((*k, *v)), name="sql-where")
        has_aggs = any(isinstance(i.expr, P.Agg) for i in ast.items)
        if has_aggs or ast.group_by:
            stream = self._plan_aggregate(ast, stream, scope)
        elif ast.having is not None:
            raise SqlError("HAVING requires GROUP BY / aggregates")
        else:
            stream = self._plan_project(ast, stream, scope)
        if ast.distinct:
            inner = stream
            stream = stream.distinct()
            # distinct re-emits the same columns: carry the SQL metadata
            # (names/nullable/string markers drive output decoding)
            for attr in ("_sql_names", "_sql_nullable_cols",
                         "_sql_str_cols"):
                if hasattr(inner, attr):
                    setattr(stream, attr, getattr(inner, attr))
        if ast.limit is not None:
            stream = self._plan_topk(ast, stream)
        return stream

    def _source_scope(self, ref: P.Source) -> Tuple[Stream, _Scope]:
        if isinstance(ref, P.SubSource):
            # FROM (query) alias: plan the subquery; its output columns are
            # visible as alias.<name> (base name = last path component)
            sub = self._plan(ref.select)
            schema = sub.schema
            dtypes = [*schema[0], *schema[1]]
            names = getattr(sub, "_sql_names", None) or \
                [f"col{i}" for i in range(len(dtypes))]
            return sub, _Scope(
                [f"{ref.alias}.{n.split('.')[-1]}" for n in names], dtypes,
                nullable=getattr(sub, "_sql_nullable_cols", ()),
                strs=getattr(sub, "_sql_str_cols", ()),
                strings=self.strings)
        if ref.name not in self.tables:
            raise SqlError(f"unknown table {ref.name}")
        stream, cols, strs, nullable = self.tables[ref.name]
        schema = stream.schema
        dtypes = [*schema[0], *schema[1]]
        return stream, _Scope([f"{ref.alias}.{c}" for c in cols], dtypes,
                              nullable=nullable, strs=strs,
                              strings=self.strings)

    def _plan_from(self, ast: P.Select) -> Tuple[Stream, _Scope]:
        """Left-deep join chain: fold each JOIN clause onto the accumulated
        (stream, scope) — the reference's Calcite plans multi-way joins the
        same left-deep way before the circuit sees them."""
        left, ls = self._source_scope(ast.table)
        for n, join in enumerate(ast.joins):
            right, rs = self._source_scope(join.table)
            if join.range is not None:
                if join.left:
                    raise SqlError("LEFT JOIN with BETWEEN bounds is not "
                                   "supported yet")
                left, ls = self._fold_range_join(join, left, ls, right, rs,
                                                 n)
            else:
                left, ls = self._fold_equi_join(join, left, ls, right, rs, n)
        return left, ls

    def _fold_equi_join(self, join: P.Join, left, ls, right, rs, n: int):
        lcol, rcol = join.on
        # resolve which side each ON column belongs to
        try:
            li = ls.index_of(lcol)
        except SqlError:
            lcol, rcol = rcol, lcol
            li = ls.index_of(lcol)
        ri = rs.index_of(rcol)
        # promote mixed-dtype ON columns to one key dtype; index_by/map_rows
        # cast their outputs to the declared schema, so both traces carry the
        # same key dtype and lex_probe never truncates probe keys
        key_dt = jnp.result_type(ls.dtypes[li], rs.dtypes[ri])

        def rekey(idx):
            def key_fn(k, v, _i=idx):
                return ((*k, *v)[_i],)

            def val_fn(k, v):
                return tuple((*k, *v))

            return key_fn, val_fn

        lk, lv = rekey(li)
        rk, rv = rekey(ri)
        lkeyed = left.index_by(lk, (key_dt,), val_fn=lv,
                               val_dtypes=tuple(ls.dtypes),
                               name=f"sql-lkey{n}")
        rkeyed = right.index_by(rk, (key_dt,), val_fn=rv,
                                val_dtypes=tuple(rs.dtypes),
                                name=f"sql-rkey{n}")
        # SQL: NULL = NULL is NULL — a NULL join key matches NOTHING.
        # Code equality would pair NULL markers, so null-keyed rows leave
        # the join inputs here; a LEFT JOIN still surfaces the left side's
        # null-keyed rows through the antijoin pad below (they match no
        # right row — exactly SQL's outcome).
        lkeyed_all = lkeyed  # pre-filter view: LEFT JOIN pads need the
        if li in ls.nullable:  # null-keyed left rows too
            lkeyed = lkeyed.filter_rows(
                lambda k, v, _n=NULL_INT(ls.dtypes[li]): k[0] != _n,
                name=f"sql-lnn{n}")
        if ri in rs.nullable:
            rkeyed = rkeyed.filter_rows(
                lambda k, v, _n=NULL_INT(rs.dtypes[ri]): k[0] != _n,
                name=f"sql-rnn{n}")
        joined = lkeyed.join_index(
            rkeyed, lambda k, lvs, rvs: (k, (*lvs, *rvs)),
            (key_dt,), (*ls.dtypes, *rs.dtypes), name=f"sql-join{n}")
        if join.left:
            # LEFT JOIN: unmatched left rows survive, right columns padded
            # with NULL_INT (the dtype's min — documented NULL convention)
            nulls = tuple(NULL_INT(dt) for dt in rs.dtypes)

            def pad(k, v, _nulls=nulls, _dts=tuple(rs.dtypes)):
                return k, (*v, *(jnp.full(v[0].shape, nv, jnp.dtype(dt))
                                 for nv, dt in zip(_nulls, _dts)))

            missing = lkeyed_all.antijoin(rkeyed).map_rows(
                pad, (key_dt,), (*ls.dtypes, *rs.dtypes),
                name=f"sql-leftpad{n}")
            joined = joined.plus(missing)
            joined.schema = ((key_dt,), (*ls.dtypes, *rs.dtypes))
        rbase = 1 + len(ls.names)
        nullable = {1 + i for i in ls.nullable} | \
            {rbase + i for i in rs.nullable}
        if join.left:
            # every right-side column may now carry the NULL pad
            nullable |= {rbase + i for i in range(len(rs.names))}
        scope = _Scope([f"__jk{n}__", *ls.names, *rs.names],
                       [key_dt, *ls.dtypes, *rs.dtypes], nullable=nullable,
                       strs={1 + i for i in ls.strs}
                       | {rbase + i for i in rs.strs},
                       strings=self.strings)
        return joined, scope

    def _fold_range_join(self, join, left, ls, right, rs, n: int):
        """JOIN r ON r.x BETWEEN l.y + c1 AND l.y + c2 -> relative range
        join (operators/join_range.py)."""
        import dbsp_tpu.operators.join_range  # noqa: F401 (register)

        rng = join.range
        try:
            ri = rs.index_of(rng.col)
        except SqlError:
            raise SqlError("range-join column must belong to the joined "
                           f"table: {rng.col}")

        def split_rel(e):
            if isinstance(e, P.Col):
                return e, 0
            if isinstance(e, P.BinOp) and e.op in ("+", "-") and \
                    isinstance(e.left, P.Col) and isinstance(e.right, P.Lit):
                c = int(e.right.value)
                return e.left, c if e.op == "+" else -c
            raise SqlError(
                "range-join bounds must be <left column> [± integer]")

        lo_col, lo_c = split_rel(rng.lo)
        hi_col, hi_c = split_rel(rng.hi)
        if (lo_col.table, lo_col.name) != (hi_col.table, hi_col.name):
            raise SqlError("range-join bounds must share one base column")
        li = ls.index_of(lo_col)
        key_dt = jnp.result_type(ls.dtypes[li], rs.dtypes[ri])

        lkeyed = left.index_by(
            lambda k, v, _i=li: ((*k, *v)[_i],), (key_dt,),
            val_fn=lambda k, v: (*k, *v), val_dtypes=tuple(ls.dtypes),
            name=f"sql-rglkey{n}")
        rkeyed = right.index_by(
            lambda k, v, _i=ri: ((*k, *v)[_i],), (key_dt,),
            val_fn=lambda k, v: (*k, *v), val_dtypes=tuple(rs.dtypes),
            name=f"sql-rgrkey{n}")
        joined = lkeyed.join_range(
            rkeyed, lo_c, hi_c,
            lambda lk, lv, rk, rv: (lk, (*lv, *rv)),
            (key_dt,), (*ls.dtypes, *rs.dtypes), name=f"sql-rangejoin{n}")
        rbase = 1 + len(ls.names)
        scope = _Scope([f"__jk{n}__", *ls.names, *rs.names],
                       [key_dt, *ls.dtypes, *rs.dtypes],
                       nullable={1 + i for i in ls.nullable}
                       | {rbase + i for i in rs.nullable},
                       strs={1 + i for i in ls.strs}
                       | {rbase + i for i in rs.strs},
                       strings=self.strings)
        return joined, scope

    # -- scalar subqueries ---------------------------------------------------
    def _bind_subqueries(self, stream, scope, where):
        """Plan each scalar subquery; cross-join its single row into the
        main stream on a unit key; rewrite the WHERE to reference it."""
        subs: List[P.Select] = []

        def rewrite(e):
            if isinstance(e, P.Subquery):
                subs.append(e.select)
                return P.Col(None, f"__sub{len(subs) - 1}__")
            if isinstance(e, P.BinOp):
                return P.BinOp(e.op, rewrite(e.left), rewrite(e.right))
            if isinstance(e, P.NotOp):
                return P.NotOp(rewrite(e.expr))
            if isinstance(e, (P.IsNull, P.InList, P.LikeOp)):
                return dataclasses.replace(e, expr=rewrite(e.expr))
            return e

        where2 = rewrite(where)
        flat_dts = list(scope.dtypes)
        names = list(scope.names)
        for i, sel in enumerate(subs):
            sub = self._plan(sel)
            sschema = sub.schema
            scols = (*sschema[0], *sschema[1])
            if len(scols) != 1:
                raise SqlError("scalar subquery must select one column")
            unit = lambda k, v: (jnp.zeros_like((*k, *v)[0]).astype(jnp.int64),)  # noqa: E731,E501
            main_ck = stream.index_by(
                unit, (jnp.int64,), val_fn=lambda k, v: (*k, *v),
                val_dtypes=tuple(flat_dts), name=f"sql-crossL{i}")
            sub_ck = sub.index_by(
                unit, (jnp.int64,), val_fn=lambda k, v: (*k, *v),
                val_dtypes=scols, name=f"sql-crossR{i}")
            stream = main_ck.join_index(
                sub_ck, lambda k, mv, sv: (k, (*mv, *sv)),
                (jnp.int64,), (*flat_dts, *scols), name=f"sql-cross{i}")
            names = [f"__cross{i}__", *names, f"__sub{i}__"]
            flat_dts = [jnp.int64, *flat_dts, scols[0]]
            # each cross-join shifts prior columns right by one (the unit
            # key lands at 0) and appends the scalar at the end
            scope = _Scope(
                names, flat_dts,
                nullable={1 + i for i in scope.nullable},
                strs={1 + i for i in scope.strs}, strings=self.strings)
        return stream, scope, where2

    # -- set membership (IN (SELECT) / EXISTS) -------------------------------
    def _restore_layout(self, keyed: Stream, scope: _Scope,
                        tag: str) -> Stream:
        """After a semijoin round-trip, put the stream back into scope's
        flat column order (all columns as keys — downstream planning only
        cares about flat order, not the key/val split)."""
        out = keyed.map_rows(lambda k, v: (v, ()), tuple(scope.dtypes), (),
                             name=f"sql-member-{tag}")
        return out

    def _lower_membership(self, m, stream: Stream, scope: _Scope) -> Stream:
        """Lower one ``expr [NOT] IN (SELECT ...)`` or ``[NOT] EXISTS``
        conjunct onto the incremental semijoin/antijoin pair
        (operators/semijoin.py; the reference's Calcite plans compile these
        predicates to the same delta-set operators). NULL discipline:
        NULL_INT-keyed rows are removed from the subquery side (a NULL
        never equals anything), and a NULL outer key on IN/EXISTS can
        never match — which is exactly SQL's row-dropping outcome for a
        NULL predicate at the WHERE boundary."""
        tag = f"m{id(m) & 0xffff:x}"
        if isinstance(m, P.InSubquery):
            sub = self._plan(m.select)
            sflat = [*sub.schema[0], *sub.schema[1]]
            svis = getattr(sub, "_sql_names", None) or \
                [f"col{i}" for i in range(len(sflat))]
            vis = [i for i, n in enumerate(svis)
                   if not (n.startswith("__") and n.endswith("__"))]
            if len(vis) != 1:
                raise SqlError("IN (SELECT ...) needs exactly one output "
                               "column")
            si = vis[0]
            s_nullable = si in getattr(sub, "_sql_nullable_cols", ())
            if m.negated and s_nullable:
                raise SqlError(
                    "NOT IN over a nullable subquery column is not "
                    "supported (SQL makes the whole predicate NULL when "
                    "the subquery contains a NULL)")
            kfn, kdt, k_nullable, k_str = _compile_proj(m.expr, scope)
            s_str = si in getattr(sub, "_sql_str_cols", ())
            if k_str != s_str:
                raise SqlError("IN (SELECT): string/number type mismatch")
            key_dt = jnp.result_type(kdt, sflat[si])
            sub_null = NULL_INT(sflat[si])

            def subkey(k, v, _i=si):
                return ((*k, *v)[_i].astype(key_dt),)

            sub_keyed = sub.index_by(subkey, (key_dt,),
                                     name=f"sql-inr-{tag}")
            if s_nullable:
                sub_keyed = sub_keyed.filter_rows(
                    lambda k, v, _n=sub_null: k[0] != _n,
                    name=f"sql-innn-{tag}")
            main_keyed = stream.index_by(
                lambda k, v, _f=kfn: (_f((*k, *v)).astype(key_dt),),
                (key_dt,), val_fn=lambda k, v: (*k, *v),
                val_dtypes=tuple(scope.dtypes), name=f"sql-inl-{tag}")
            if m.negated and k_nullable:
                # NULL NOT IN (non-null set) is NULL -> row dropped. The
                # projection marked NULLs with the EXPRESSION dtype's
                # marker before widening to key_dt (widening preserves
                # the value), so that is what the filter must match.
                main_keyed = main_keyed.filter_rows(
                    lambda k, v, _n=NULL_INT(kdt): k[0] != _n,
                    name=f"sql-inln-{tag}")
            joined = main_keyed.antijoin(sub_keyed) if m.negated \
                else main_keyed.semijoin(sub_keyed)
            return self._restore_layout(joined, scope, tag)

        assert isinstance(m, P.ExistsOp)
        if not isinstance(m.select, P.Select):
            raise SqlError("EXISTS needs a plain SELECT subquery")
        sub_ast = m.select
        if sub_ast.group_by or sub_ast.having is not None or \
                sub_ast.limit is not None:
            # these clauses change which rows exist (HAVING can empty a
            # group, LIMIT 0 everything) — refusing beats silently
            # planning FROM+WHERE only
            raise SqlError("EXISTS subqueries with GROUP BY/HAVING/LIMIT "
                           "are not supported")
        sub_stream, sub_scope = self._plan_from(sub_ast)
        # decorrelate: equality conjuncts linking one sub column and one
        # outer column become semijoin keys; everything else stays a
        # sub-local predicate (inner scope shadows outer on ambiguity)
        corr: List[Tuple[int, int]] = []   # (outer idx, sub idx)
        local: List = []
        if sub_ast.where is not None:
            plain, members = _split_conjuncts(sub_ast.where)
            if members:
                raise SqlError("nested EXISTS/IN inside EXISTS is not "
                               "supported")
            conj = []

            def flat_and(e):
                if isinstance(e, P.BinOp) and e.op == "and":
                    flat_and(e.left)
                    flat_and(e.right)
                else:
                    conj.append(e)

            if plain is not None:
                flat_and(plain)
            for e in conj:
                pair = None
                if isinstance(e, P.BinOp) and e.op == "=" and \
                        isinstance(e.left, P.Col) and \
                        isinstance(e.right, P.Col):
                    for a, b in ((e.left, e.right), (e.right, e.left)):
                        try:
                            sub_scope.index_of(a)
                            continue  # resolves inside: not a correlation
                        except SqlError:
                            pass
                        try:
                            pair = (scope.index_of(a),
                                    sub_scope.index_of(b))
                            break
                        except SqlError:
                            pair = None
                if pair is not None:
                    corr.append(pair)
                else:
                    local.append(e)
        if local:
            pred = None
            for e in local:
                pred = e if pred is None else P.BinOp("and", pred, e)
            pfn = _compile_pred(pred, sub_scope)
            sub_stream = sub_stream.filter_rows(
                lambda k, v, _p=pfn: _p((*k, *v)), name=f"sql-exw-{tag}")
        if corr:
            o_idx, s_idx = zip(*corr)
            key_dts = tuple(jnp.result_type(scope.dtypes[o],
                                            sub_scope.dtypes[s])
                            for o, s in corr)
        else:
            o_idx, s_idx = (), ()
            key_dts = (jnp.int64,)
        o_null = tuple(NULL_INT(d) for d in key_dts)

        def okey(k, v, _i=o_idx):
            cols = (*k, *v)
            if not _i:
                return (jnp.zeros_like(cols[0]).astype(jnp.int64),)
            return tuple(cols[i].astype(d) for i, d in zip(_i, key_dts))

        def skey(k, v, _i=s_idx):
            cols = (*k, *v)
            if not _i:
                return (jnp.zeros_like(cols[0]).astype(jnp.int64),)
            return tuple(cols[i].astype(d) for i, d in zip(_i, key_dts))

        sub_keyed = sub_stream.index_by(skey, key_dts,
                                        name=f"sql-exr-{tag}")
        drop_null_subkeys = tuple(j for j, s in enumerate(s_idx)
                                  if s in sub_scope.nullable)
        if drop_null_subkeys:
            def no_null_key(k, v, _j=drop_null_subkeys, _n=o_null):
                bad = None
                for j in _j:
                    b = k[j] == _n[j]
                    bad = b if bad is None else (bad | b)
                return ~bad

            sub_keyed = sub_keyed.filter_rows(no_null_key,
                                              name=f"sql-exnn-{tag}")
        main_keyed = stream.index_by(
            okey, key_dts, val_fn=lambda k, v: (*k, *v),
            val_dtypes=tuple(scope.dtypes), name=f"sql-exl-{tag}")
        joined = main_keyed.antijoin(sub_keyed) if m.negated \
            else main_keyed.semijoin(sub_keyed)
        return self._restore_layout(joined, scope, tag)

    def _plan_project(self, ast: P.Select, stream: Stream, scope: _Scope
                      ) -> Stream:
        if len(ast.items) == 1 and isinstance(ast.items[0].expr, P.Col) \
                and ast.items[0].expr.name == "*":
            # internal plumbing columns (join keys, cross-join units,
            # subquery scalars — all dunder-named) are not user-visible
            visible = [i for i, n in enumerate(scope.names)
                       if not (n.startswith("__") and n.endswith("__"))]
            if len(visible) == len(scope.names):
                stream._sql_names = list(scope.names)
                stream._sql_nullable_cols = set(scope.nullable)
                stream._sql_str_cols = set(scope.strs)
                return stream
            out = stream.map_rows(
                lambda k, v, _i=tuple(visible): (
                    tuple((*k, *v)[i] for i in _i), ()),
                tuple(scope.dtypes[i] for i in visible), (),
                name="sql-star")
            out._sql_names = [scope.names[i] for i in visible]
            out._sql_nullable_cols = {j for j, i in enumerate(visible)
                                      if i in scope.nullable}
            out._sql_str_cols = {j for j, i in enumerate(visible)
                                 if i in scope.strs}
            return out
        fns, dts, nullable, strs = [], [], set(), set()
        for j, item in enumerate(ast.items):
            fn, dt, may_null, is_str = _compile_proj(item.expr, scope)
            fns.append(fn)
            dts.append(dt)
            if may_null:
                nullable.add(j)
            if is_str:
                strs.add(j)

        def project(k, v):
            cols = (*k, *v)
            outs = tuple(jnp.broadcast_to(f(cols), cols[0].shape)
                         for f in fns)
            return outs, ()

        out = stream.map_rows(project, tuple(dts), (), name="sql-project")
        out._sql_names = _item_names(ast.items)
        # exact NULL tracking: _compile_proj re-marks NULL rows with
        # NULL_INT, so a column is nullable iff its expression can go NULL
        out._sql_nullable_cols = nullable
        out._sql_str_cols = strs
        return out

    def _plan_aggregate(self, ast: P.Select, stream: Stream, scope: _Scope
                        ) -> Stream:
        group_idx = [scope.index_of(c) for c in ast.group_by]
        key_dts = [scope.dtypes[i] for i in group_idx] or [jnp.int64]

        aggs: List[Tuple[int, P.Agg]] = []
        for pos, item in enumerate(ast.items):
            if isinstance(item.expr, P.Agg):
                aggs.append((pos, item.expr))
            elif isinstance(item.expr, P.Col):
                if scope.index_of(item.expr) not in group_idx:
                    raise SqlError(
                        f"{item.expr} must appear in GROUP BY or an aggregate")
            else:
                raise SqlError("non-aggregate select items must be columns")
        # aggregates referenced only by HAVING are computed but not projected
        having_aggs = _collect_aggs(ast.having) if ast.having else []
        selected = [a for _, a in aggs]
        for ha in having_aggs:
            if ha not in selected:
                aggs.append((None, ha))
                selected.append(ha)

        def agg_arg(agg: P.Agg):
            """(arg projection, dtype, nullable) for one aggregate."""
            if agg.arg is None:
                return (lambda cols: jnp.ones_like(cols[0])), \
                    np.dtype(np.int64), False
            fn, dt, may_null, is_str = _compile_proj(agg.arg, scope)
            if is_str and agg.fn != "count":
                raise SqlError(
                    f"{agg.fn.upper()} over a string column is not defined "
                    "(dictionary codes carry equality only)")
            return fn, dt, may_null

        def keyed_stream(agg: P.Agg, arg_fn, arg_dt) -> Stream:
            def mapper(k, v, _f=arg_fn, _dt=arg_dt):
                cols = (*k, *v)
                keys = tuple(cols[i] for i in group_idx) or \
                    (jnp.zeros_like(cols[0]),)
                # NULL propagation happens inside _compile_proj's fn: NULL
                # rows already carry NULL_INT in the projected argument
                out = jnp.broadcast_to(_f(cols), cols[0].shape)
                return keys, (out,)

            return stream.map_rows(mapper, tuple(key_dts), (arg_dt,),
                                   name="sql-keyed")

        # an aggregate is NULL-aware iff its argument expression can go
        # NULL (SQL semantics: aggregates skip NULLs; all-NULL groups
        # aggregate to NULL, COUNT to 0). Everything else keeps the linear
        # fast path.
        results = []
        null_aware: Dict[int, bool] = {}
        for slot, (pos, agg) in enumerate(aggs):
            arg_fn, arg_dt, may_null = agg_arg(agg)
            ks = keyed_stream(agg, arg_fn, arg_dt)
            null_aware[slot] = may_null
            if may_null:
                inst = _SqlNullAgg(agg.fn)
            else:
                cls = AGG_CLASSES[agg.fn]
                inst = cls() if agg.fn == "count" else cls(0)
            results.append(ks.aggregate(inst, name=f"sql-{agg.fn}"))
        combined = results[0]
        for extra in results[1:]:
            n = len(combined.schema[1])
            combined = combined.join_index(
                extra, lambda k, a, b: (k, (*a, *b)),
                tuple(key_dts),
                (*combined.schema[1], *extra.schema[1]), name="sql-aggjoin")

        if ast.having is not None:
            # evaluate the HAVING predicate over (group keys, agg columns):
            # rewrite Agg nodes to their slot in combined's value columns
            # and group columns to their key slot. NULL-aware agg slots and
            # nullable/string group columns keep their markings so the
            # predicate runs the same three-valued logic as WHERE.
            hscope = _Scope(
                [f"__g{i}__" for i in range(len(group_idx))] +
                [f"__a{j}__" for j in range(len(aggs))],
                [*key_dts, *([jnp.int64] * len(aggs))],
                nullable={i for i, gi in enumerate(group_idx)
                          if gi in scope.nullable}
                | {len(group_idx) + j for j in range(len(aggs))
                   if null_aware[j]},
                strs={i for i, gi in enumerate(group_idx)
                      if gi in scope.strs},
                strings=scope.strings)

            def hrewrite(e):
                if isinstance(e, P.Agg):
                    return P.Col(None, f"__a{selected.index(e)}__")
                if isinstance(e, P.Col):
                    gi = group_idx.index(scope.index_of(e))
                    return P.Col(None, f"__g{gi}__")
                if isinstance(e, P.BinOp):
                    return P.BinOp(e.op, hrewrite(e.left), hrewrite(e.right))
                if isinstance(e, P.NotOp):
                    return P.NotOp(hrewrite(e.expr))
                if isinstance(e, (P.IsNull, P.InList, P.LikeOp)):
                    return dataclasses.replace(e, expr=hrewrite(e.expr))
                return e

            pred = _compile_pred(hrewrite(ast.having), hscope)
            combined = combined.filter_rows(
                lambda k, v, _p=pred: _p((*k, *v)), name="sql-having")

        # order output columns as selected: group cols come from the key
        agg_positions = {pos: i for i, (pos, _) in enumerate(aggs)
                         if pos is not None}

        def finalize(k, v):
            outs = []
            for pos, item in enumerate(ast.items):
                if pos in agg_positions:
                    outs.append(v[agg_positions[pos]])
                else:
                    outs.append(k[group_idx.index(
                        scope.index_of(item.expr))])
            return tuple(outs), ()

        out_dts = []
        for pos, item in enumerate(ast.items):
            if pos in agg_positions:
                out_dts.append(jnp.int64)
            else:
                out_dts.append(scope.dtypes[scope.index_of(item.expr)])
        out = combined.map_rows(finalize, tuple(out_dts), (),
                                name="sql-finalize")
        out._sql_names = _item_names(ast.items)
        # NULL-aware aggregates can emit NULL (all-NULL groups); group
        # columns inherit their source column's nullability/string-ness
        out._sql_nullable_cols = {
            pos for pos, item in enumerate(ast.items)
            if (pos in agg_positions and null_aware[agg_positions[pos]])
            or (pos not in agg_positions
                and scope.index_of(item.expr) in scope.nullable)}
        out._sql_str_cols = {
            pos for pos, item in enumerate(ast.items)
            if pos not in agg_positions
            and scope.index_of(item.expr) in scope.strs}
        return out

    def _plan_topk(self, ast: P.Select, stream: Stream) -> Stream:
        """ORDER BY ... LIMIT n -> global top-K: re-key to a unit key with
        the order columns leading the value tuple, take K, restore layout."""
        names = getattr(stream, "_sql_names", None)
        schema = stream.schema
        flat_dts = [*schema[0], *schema[1]]
        if names is None:
            names = [f"col{i}" for i in range(len(flat_dts))]
        aux = _Scope(names, flat_dts)
        order_idx = [aux.index_of(o.col) for o in ast.order_by]
        strs = getattr(stream, "_sql_str_cols", set())
        if any(i in strs for i in order_idx):
            raise SqlError("ORDER BY over string columns is not supported "
                           "(dictionary codes are unordered)")
        descs = {o.desc for o in ast.order_by}
        if len(descs) > 1:
            raise SqlError("mixed ASC/DESC ORDER BY is not supported yet")
        desc = descs.pop() if descs else False
        rest = [i for i in range(len(flat_dts)) if i not in order_idx]
        perm = [*order_idx, *rest]
        inv = [perm.index(i) for i in range(len(flat_dts))]

        keyed = stream.index_by(
            lambda k, v: (jnp.zeros_like((*k, *v)[0]).astype(jnp.int64),),
            (jnp.int64,),
            val_fn=lambda k, v, _p=tuple(perm): tuple((*k, *v)[i]
                                                      for i in _p),
            val_dtypes=tuple(flat_dts[i] for i in perm), name="sql-orderkey")
        top = keyed.topk(ast.limit, largest=desc, name="sql-limit")
        out = top.map_rows(
            lambda k, v, _i=tuple(inv): (tuple(v[i] for i in _i), ()),
            tuple(flat_dts), (), name="sql-unorder")
        out._sql_names = names
        out._sql_nullable_cols = set(
            getattr(stream, "_sql_nullable_cols", ()))
        out._sql_str_cols = set(getattr(stream, "_sql_str_cols", ()))
        return out
