"""Deterministic fault-injection harness for durability testing.

Four fault families, each seeded/deterministic so a failing run replays
exactly (the harness is the proof side of README §Durability):

* **Kill-at-tick** — a pipeline runs as a SUBPROCESS
  (``python -m dbsp_tpu.testing.faults --serve <config.json>``) writing a
  per-tick status file and a per-tick output-delta JSONL; the parent
  watches the status file and SIGKILLs the child the moment it passes the
  planned tick. SIGKILL (not SIGTERM) means no atexit/flush runs — the
  crash the checkpoint store's atomic-generation discipline must survive.
  The child re-launched with ``"resume": true`` restores the newest valid
  checkpoint generation and continues, and its subsequent delta stream
  must be bit-identical to an uninterrupted run's (tests/test_faults.py
  proves this for Nexmark q4 in host AND compiled modes).

* **Transport chaos** — :func:`transport_chaos` monkeypatches the
  minikafka client connection to fail its first N connects/reads with
  ``ConnectionError`` (deterministic counters, not probabilities),
  exercising the bounded-backoff retry path
  (``dbsp_tpu_io_transport_retries_total``) and, past the retry budget,
  the endpoint-terminates-instead-of-hanging contract.

* **Slow consumer** — :class:`StallingOutputTransport` stalls ``write``
  for a configured duration every Nth delivery (a backpressured sink);
  the controller must keep serving control/status traffic and deliver
  everything once the stall clears.

* **Checkpoint corruption** — :func:`corrupt_checkpoint` flips/truncates
  bytes in the CURRENT generation's manifest or a seeded-chosen blob;
  restore must fall back to the previous generation and surface exactly
  one SLO-visible ``restore`` incident.

* **Seeded interleavings** — :class:`InterleaveSchedule` injects sleeps
  at the yield points the concurrency sanitizer's instrumented locks
  expose (``dbsp_tpu.testing.tsan.set_schedule``), widening the thread
  schedules a hammer test explores. Deterministic per seed: the decision
  SEQUENCE (which acquire/release yields) replays exactly; what the OS
  scheduler does with each yield is the explored dimension.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from dbsp_tpu.io.transport import OutputTransport


@dataclasses.dataclass
class FaultPlan:
    """A deterministic fault schedule for one harness run."""

    seed: int = 1
    kill_at_tick: Optional[int] = None   # SIGKILL once status passes this
    fail_connects: int = 0               # transport: first N connects fail
    fail_reads: int = 0                  # transport: first N reads fail


# ---------------------------------------------------------------------------
# Kill-at-tick: pipeline child process + parent-side controls
# ---------------------------------------------------------------------------


def child_config(mode: str, checkpoint_dir: str, status_path: str,
                 out_path: str, query: str = "q4", ticks: int = 24,
                 batch: int = 250, seed: int = 1, checkpoint_every: int = 5,
                 resume: bool = False, validate_every: int = 1) -> dict:
    """The JSON config a pipeline child runs from (see :func:`_serve`)."""
    return {"mode": mode, "query": query, "ticks": int(ticks),
            "batch": int(batch), "seed": int(seed),
            "checkpoint_dir": checkpoint_dir,
            "checkpoint_every": int(checkpoint_every),
            "status_path": status_path, "out_path": out_path,
            "resume": bool(resume), "validate_every": int(validate_every)}


def spawn_child(cfg: dict, cfg_path: str) -> "subprocess.Popen":
    """Launch one pipeline child (inherits the environment — test runs
    pass JAX_PLATFORMS=cpu and the shared compile cache through it)."""
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    import dbsp_tpu

    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(dbsp_tpu.__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "dbsp_tpu.testing.faults", "--serve",
         cfg_path],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True)


def read_status(status_path: str) -> Optional[dict]:
    try:
        with open(status_path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None  # not written yet / mid-replace


def wait_for_tick(status_path: str, tick: int, proc=None,
                  timeout_s: float = 300.0) -> dict:
    """Block until the child's status file reports ``tick`` (or beyond).
    Raises on timeout or child death."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        st = read_status(status_path)
        if st is not None and st.get("tick", -1) >= tick:
            return st
        if proc is not None and proc.poll() is not None:
            err = proc.stderr.read() if proc.stderr else ""
            raise RuntimeError(
                f"pipeline child exited rc={proc.returncode} before tick "
                f"{tick}: {err[-2000:]}")
        time.sleep(0.02)
    raise TimeoutError(f"child never reached tick {tick}")


def kill9(proc: "subprocess.Popen") -> None:
    """SIGKILL — the crash no handler sees (atomic checkpoint proof)."""
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30)


def run_child(cfg: dict, cfg_path: str, timeout_s: float = 600.0) -> dict:
    """Run one child to completion; returns its final status."""
    proc = spawn_child(cfg, cfg_path)
    try:
        rc = proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise
    if rc != 0:
        err = proc.stderr.read() if proc.stderr else ""
        raise RuntimeError(f"pipeline child failed rc={rc}: {err[-2000:]}")
    st = read_status(cfg["status_path"])
    if st is None or not st.get("done"):
        raise RuntimeError(f"child exited without finishing: {st}")
    return st


def read_deltas(out_path: str) -> Dict[int, list]:
    """tick -> sorted delta rows from a child's output JSONL. A torn final
    line (the SIGKILL case) is dropped — its tick replays after restore."""
    out: Dict[int, list] = {}
    try:
        with open(out_path) as f:
            for line in f:
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue  # torn write at the kill point
                if "tick" in obj:
                    out[obj["tick"]] = obj["delta"]
    except OSError:
        pass
    return out


# ---------------------------------------------------------------------------
# Transport chaos
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def transport_chaos(fail_connects: int = 0, fail_reads: int = 0):
    """Deterministically fail the first N minikafka connects and/or
    request round-trips with ``ConnectionError`` (counted process-wide
    across connections while active). Exercises the retry/backoff path;
    with N past the retry budget, the terminal-failure path."""
    from dbsp_tpu.io import minikafka

    counters = {"connects": 0, "reads": 0}
    orig_connect = minikafka._Conn._connect
    orig_roundtrip = minikafka._Conn._roundtrip

    def chaotic_connect(self):
        counters["connects"] += 1
        if counters["connects"] <= fail_connects:
            raise ConnectionError(
                f"injected connect failure #{counters['connects']}")
        return orig_connect(self)

    def chaotic_roundtrip(self, payload):
        counters["reads"] += 1
        if counters["reads"] <= fail_reads:
            raise ConnectionError(
                f"injected read failure #{counters['reads']}")
        return orig_roundtrip(self, payload)

    minikafka._Conn._connect = chaotic_connect
    minikafka._Conn._roundtrip = chaotic_roundtrip
    try:
        yield counters
    finally:
        minikafka._Conn._connect = orig_connect
        minikafka._Conn._roundtrip = orig_roundtrip


class StallingOutputTransport(OutputTransport):
    """Output sink that stalls every ``every``-th write for ``stall_s`` —
    the slow-consumer fault. Collects everything it was given so tests
    can assert nothing was lost once the stalls cleared."""

    name = "stalling_output"

    def __init__(self, stall_s: float = 0.2, every: int = 2):
        self.stall_s = float(stall_s)
        self.every = max(1, int(every))
        self.writes = 0
        self.stalls = 0
        self.chunks: List[bytes] = []

    def write(self, data: bytes) -> None:
        self.writes += 1
        if self.writes % self.every == 0:
            self.stalls += 1
            time.sleep(self.stall_s)
        self.chunks.append(data)


# ---------------------------------------------------------------------------
# Seeded interleaving schedules (yield-point injection at traced locks)
# ---------------------------------------------------------------------------


class InterleaveSchedule:
    """Seeded yield-point injector for the runtime concurrency sanitizer.

    Installed via ``tsan.set_schedule`` (or ``tsan.session(schedule=...)``)
    it is called at every instrumented lock acquire/release with the
    event kind and the lock's ``Class.attr`` name. With probability
    ``rate`` (decided by a seeded RNG, so the decision sequence is
    deterministic) it sleeps ``sleep_s`` — long enough that any runnable
    peer thread gets scheduled into the window the yield opens. This is
    the deliberate-interleaving half of ThreadSanitizer's recipe: races
    that need a narrow preemption window (check-then-act on a shared
    field, a reader between a clear and a refill) reproduce under the
    widened schedule instead of once a quarter in production.

    ``only`` restricts injection to lock names containing any of the
    given substrings (e.g. ``("Controller.",)``); ``max_yields`` bounds
    total injected sleeps so a hammer test's duration stays bounded.
    """

    def __init__(self, seed: int = 1, rate: float = 0.25,
                 sleep_s: float = 0.002, max_yields: int = 2000,
                 only: Optional[tuple] = None):
        self.rng = random.Random(seed)
        self.rate = float(rate)
        self.sleep_s = float(sleep_s)
        self.max_yields = int(max_yields)
        self.only = tuple(only) if only else None
        self.yields = 0
        self.decisions = 0
        self._lock = threading.Lock()

    def yield_point(self, hook: str, lock_name: str) -> None:
        if self.only is not None and \
                not any(s in lock_name for s in self.only):
            return
        with self._lock:
            self.decisions += 1
            if self.yields >= self.max_yields:
                return
            fire = self.rng.random() < self.rate
            if fire:
                self.yields += 1
        if fire:
            time.sleep(self.sleep_s)


# ---------------------------------------------------------------------------
# Checkpoint corruption
# ---------------------------------------------------------------------------


def corrupt_checkpoint(path: str, kind: str = "blob", seed: int = 0) -> str:
    """Deterministically corrupt the CURRENT generation: ``"manifest"``
    scribbles over manifest.json, ``"blob"`` flips a byte mid-file in a
    seeded-chosen array blob, ``"truncate"`` cuts one in half. Returns the
    corrupted file's path."""
    with open(os.path.join(path, "CURRENT")) as f:
        gen = f.read().strip()
    gen_dir = os.path.join(path, gen)
    if kind == "manifest":
        target = os.path.join(gen_dir, "manifest.json")
        with open(target, "r+b") as f:
            f.seek(max(0, os.path.getsize(target) // 2))
            f.write(b"\x00CORRUPT\x00")
        return target
    blobs = sorted(n for n in os.listdir(gen_dir) if n.endswith(".npy"))
    if not blobs:
        raise ValueError(f"no blobs to corrupt in {gen_dir}")
    # prefer blobs EXCLUSIVE to this generation (nlink == 1): the fault
    # being modeled is a torn/corrupted fresh write — clean deep levels
    # are hard-linked across generations (one inode), so scribbling on
    # one would corrupt every generation sharing it, which is media
    # bitrot, not a crash mode the generation store claims to survive
    exclusive = [n for n in blobs
                 if os.stat(os.path.join(gen_dir, n)).st_nlink == 1]
    target = os.path.join(
        gen_dir, random.Random(seed).choice(exclusive or blobs))
    size = os.path.getsize(target)
    if kind == "truncate":
        with open(target, "r+b") as f:
            f.truncate(max(1, size // 2))
    elif kind == "blob":
        with open(target, "r+b") as f:
            f.seek(max(0, size - 3))  # flip payload bytes, not the header
            b = f.read(1)
            f.seek(max(0, size - 3))
            f.write(bytes([b[0] ^ 0xFF]))
    else:
        raise ValueError(f"unknown corruption kind {kind!r}")
    return target


# ---------------------------------------------------------------------------
# Child main: a checkpointing Nexmark pipeline driven tick-by-tick
# ---------------------------------------------------------------------------


def _write_status(path: str, obj: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _serve(cfg: dict) -> int:
    """Child entry: run ``ticks`` deterministic Nexmark ticks through a
    controller-owned pipeline (host or compiled driver) with periodic
    checkpointing, recording each tick's output delta durably (fsync per
    line, so a SIGKILL tears at most the final line). With ``resume``,
    restores the newest valid checkpoint generation first and continues
    from its tick — the inputs are a function of (seed, tick), so the
    replay past the checkpoint is exact."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.io import Catalog
    from dbsp_tpu.io.controller import Controller, ControllerConfig
    from dbsp_tpu.nexmark import (GeneratorConfig, NexmarkGenerator,
                                  build_inputs, queries)

    query = getattr(queries, cfg["query"])

    def build(c):
        streams, handles = build_inputs(c)
        return handles, query(*streams).output()

    handle, (handles, out) = Runtime.init_circuit(1, build)
    driver = handle
    if cfg["mode"] == "compiled":
        from dbsp_tpu.compiled.driver import CompiledCircuitDriver

        driver = CompiledCircuitDriver(
            handle, validate_every=cfg.get("validate_every", 1))
    ctl = Controller(driver, Catalog(), ControllerConfig(
        checkpoint_dir=cfg["checkpoint_dir"],
        checkpoint_every_ticks=cfg.get("checkpoint_every", 0)))
    start_tick = 0
    restored = None
    if cfg.get("resume"):
        from dbsp_tpu import checkpoint as ckpt

        if ckpt.exists(cfg["checkpoint_dir"]):
            restored = ctl.restore_from()
            start_tick = ctl.steps
    gen = NexmarkGenerator(GeneratorConfig(seed=cfg.get("seed", 1)))
    cursor = out.register_consumer()
    batch = cfg["batch"]
    with open(cfg["out_path"], "w") as outf:
        outf.write(json.dumps({
            "header": True, "start_tick": start_tick,
            "restored_tick": restored["tick"] if restored else None,
            "fallback_from": (restored or {}).get("fallback_from"),
        }) + "\n")
        outf.flush()
        os.fsync(outf.fileno())
        for t in range(start_tick, cfg["ticks"]):
            gen.feed(handles, t * batch, (t + 1) * batch)
            ctl.step()
            b = out.read_consumer(cursor)
            delta = {} if b is None else b.to_dict()
            rows = sorted([list(k) + [int(w)] for k, w in delta.items()])
            outf.write(json.dumps({"tick": t, "delta": rows}) + "\n")
            outf.flush()
            os.fsync(outf.fileno())
            _write_status(cfg["status_path"], {"tick": t})
    ctl.stop()  # graceful: flush + final checkpoint generation
    _write_status(cfg["status_path"],
                  {"tick": cfg["ticks"] - 1, "done": True,
                   "start_tick": start_tick,
                   "checkpoints": ctl.checkpoints,
                   "last_checkpoint_tick": ctl.last_checkpoint_tick})
    # every durable artifact is fsynced above; skip interpreter teardown,
    # where XLA:CPU executable destructors have been observed to segfault
    # (the parent would misread a clean run as a crash)
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)


def main(argv: Optional[List[str]] = None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) == 2 and argv[0] == "--serve":
        with open(argv[1]) as f:
            return _serve(json.load(f))
    print("usage: python -m dbsp_tpu.testing.faults --serve <config.json>",
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
