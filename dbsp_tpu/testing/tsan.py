"""Runtime concurrency sanitizer: declared-guard enforcement + Eraser
lockset inference + lock-order inversion detection.

The runtime half of the concurrency sanitizer (the static half is
``tools/check_concurrency.py``). Under ``DBSP_TPU_TSAN=1`` — or inside a
:func:`session` — every serving-plane object registered in
:data:`dbsp_tpu.concurrency.CONCURRENCY_SCHEMA` is instrumented at
construction (``maybe_instrument`` hooks at the end of each ``__init__``):

* its ``threading.Lock``/``RLock`` fields are wrapped in
  :class:`TracedLock` — acquire/release maintain a per-thread held-lock
  set, feed the global lock-ORDER graph (an A->B acquisition observed
  after a B->A acquisition is an inversion violation, no deadlock
  required — ThreadSanitizer's deadlock detector idiom, Serebryany &
  Iskhodzhanov, WBIA'09), and call the installed
  :class:`~dbsp_tpu.testing.faults.InterleaveSchedule` yield points so a
  seeded fuzz run explores more interleavings;
* its class is swapped for a generated subclass whose
  ``__getattribute__``/``__setattr__`` trace the schema'd fields and
  enforce each field's declared guard:

  ====================  ====================================================
  ``lock(L)``           every access must hold the instance's ``L``
  ``writelock(L)``      every WRITE must hold ``L``
  ``owner``             all accesses from one thread (recorded at first
                        access after instrumentation)
  ``lockset``           Eraser (Savage et al., TOCS'97) over writes: once a
                        second thread writes, the intersection of lock sets
                        held across all writes must stay non-empty
  ``immutable``         no rebinding after construction
  ``gil-atomic``        exempt (the schema carries the invariant)
  ====================  ====================================================

  ``lock``/``writelock`` fields additionally run the Eraser candidate-set
  bookkeeping as evidence: every violation report carries the lockset that
  protected the field so far, the guard the schema declared, the accessing
  thread, and a trimmed stack.

Violations are collected process-wide; :func:`check` raises
:class:`TsanViolations` when any were recorded — the structured report
that fails tests. Violations are NOT waivable at runtime: fix the race or
change the schema claim (``# concurrency: ok`` only waives static
findings).

Typical test shape::

    from dbsp_tpu.testing import tsan

    with tsan.session() as report:
        ... build pipeline, hammer it from threads ...
    assert report.violations == []        # or tsan.check() to raise

Overhead: attribute tracing costs one dict lookup on traced-field access
of instrumented instances only; with the sanitizer disabled the
construction hooks are a single module-flag check and instances are left
untouched, so production pays nothing.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

from dbsp_tpu.concurrency import CONCURRENCY_SCHEMA, Guard, parse_guard

__all__ = [
    "TracedLock", "TsanViolations", "enable", "disable", "enabled",
    "session", "instrument", "maybe_instrument", "violations", "check",
    "reset", "set_schedule", "dryrun",
]

_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))

# -- process-wide sanitizer state (its own untraced lock; RLock because
# the access handler holds it across _record/_eraser calls) ----------------
_state_lock = threading.RLock()
_ACTIVE = os.environ.get("DBSP_TPU_TSAN", "0") not in ("", "0")
_VIOLATIONS: List[dict] = []
_SEEN: Set[Tuple] = set()          # dedup key per (kind, cls, field, ...)
_ORDER: Dict[Tuple[str, str], List[str]] = {}   # (a, b) -> example stack
_SCHEDULE = None                   # faults.InterleaveSchedule or None
_tls = threading.local()


def _held() -> List["TracedLock"]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _stack(limit: int = 14) -> List[str]:
    out = []
    for f in traceback.extract_stack(limit=limit + 4)[:-3]:
        if f.filename.endswith(("tsan.py",)):
            continue
        out.append(f"{os.path.basename(f.filename)}:{f.lineno} {f.name}")
    return out[-limit:]


def _record(kind: str, dedup_key: Tuple, **fields) -> None:
    with _state_lock:
        if dedup_key in _SEEN:
            for v in _VIOLATIONS:
                if v.get("_key") == dedup_key:
                    v["count"] += 1
                    break
            return
        _SEEN.add(dedup_key)
        _VIOLATIONS.append(dict(kind=kind, count=1, _key=dedup_key,
                                thread=threading.current_thread().name,
                                stack=_stack(), **fields))


class TsanViolations(AssertionError):
    """Raised by :func:`check`; carries the structured reports."""

    def __init__(self, reports: List[dict]):
        self.reports = reports
        lines = [f"{len(reports)} concurrency violation(s):"]
        for r in reports:
            lines.append(
                f"  [{r['kind']}] {r.get('cls')}.{r.get('field')} "
                f"guard={r.get('guard')} thread={r['thread']} "
                f"x{r['count']}")
            for s in r.get("stack", [])[-4:]:
                lines.append(f"      {s}")
        super().__init__("\n".join(lines))


# ---------------------------------------------------------------------------
# traced locks
# ---------------------------------------------------------------------------


class TracedLock:
    """Wraps a ``threading.Lock``/``RLock``: held-set bookkeeping, lock-
    order graph edges, and yield-point injection for the interleaving
    fuzzer. Context-manager compatible with the wrapped lock."""

    __slots__ = ("real", "name")

    def __init__(self, real, name: str):
        self.real = real
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sched = _SCHEDULE
        if sched is not None:
            sched.yield_point("acquire", self.name)
        ok = self.real.acquire(blocking, timeout)
        if ok:
            held = _held()
            if not any(lk is self for lk in held):  # re-entrant: no edges
                self._edges(held)
            held.append(self)
        return ok

    def release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self.real.release()
        sched = _SCHEDULE
        if sched is not None:
            sched.yield_point("release", self.name)

    def _edges(self, held: List["TracedLock"]) -> None:
        for prev in held:
            if prev is self or prev.name == self.name:
                continue  # same class.attr on two instances: ambiguous
            edge = (prev.name, self.name)
            with _state_lock:
                known = edge in _ORDER
                if not known:
                    _ORDER[edge] = _stack()
                inverse = _ORDER.get((self.name, prev.name))
            if inverse is not None:
                _record(
                    "lock-order-inversion",
                    ("order", self.name, prev.name) if
                    self.name < prev.name else ("order", prev.name,
                                                self.name),
                    cls=self.name.split(".")[0],
                    field=self.name.split(".", 1)[1],
                    guard=f"{prev.name} -> {self.name} inverts an "
                          f"observed {self.name} -> {prev.name}",
                    inverse_stack=inverse[-6:])

    def locked(self) -> bool:
        return self.real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<TracedLock {self.name}>"


# ---------------------------------------------------------------------------
# attribute tracing
# ---------------------------------------------------------------------------


def _mro_guards(base: type) -> Dict[str, Guard]:
    out: Dict[str, Guard] = {}
    for klass in reversed(base.__mro__):
        entry = CONCURRENCY_SCHEMA.get(klass.__name__)
        if entry:
            for attr, value in entry.items():
                out[attr] = parse_guard(value)
    return out


def _on_access(obj, field: str, guard: Guard, write: bool) -> None:
    if getattr(_tls, "in_handler", False):
        return
    _tls.in_handler = True
    try:
        st = object.__getattribute__(obj, "__tsan__")
        held = _held()
        held_names = tuple(lk.name for lk in held)
        cls = type(obj).__tsan_base__.__name__
        tname = threading.current_thread().name
        # the detector's own per-field state (owner, Eraser candidate
        # set) is shared across the very threads it watches — mutate it
        # only under the sanitizer lock, or a check-then-act race INSIDE
        # the race detector drops violations (two first-writers both
        # seeding cand, two first-accessors both claiming owner)
        with _state_lock:
            fs = st["fields"].setdefault(field, {
                "owner": None, "writers": set(), "cand": None})
            _check_access(obj, st, fs, field, guard, write, held,
                          held_names, cls, tname)
    finally:
        _tls.in_handler = False


def _check_access(obj, st, fs, field, guard, write, held, held_names,
                  cls, tname):  # holds: _state_lock
    if guard.kind in ("lock", "writelock"):
        if guard.kind == "lock" or write:
            target = st["locks"].get(guard.lock)
            if target is not None and \
                    not any(lk is target for lk in held):
                _record(
                    "declared-guard",
                    ("guard", cls, field, write, tname),
                    cls=cls, field=field,
                    guard=f"{guard.kind}({guard.lock})",
                    access="write" if write else "read",
                    held=held_names,
                    lockset=sorted(fs["cand"] or ()))
        # Eraser evidence channel over writes
        if write:
            _eraser(fs, {lk.name for lk in held}, tname, cls, field,
                    f"{guard.kind}({guard.lock})")
    elif guard.kind == "owner":
        if fs["owner"] is None:
            fs["owner"] = tname
        elif fs["owner"] != tname:
            _record("owner-violation",
                    ("owner", cls, field, tname),
                    cls=cls, field=field, guard="owner",
                    access="write" if write else "read",
                    first_owner=fs["owner"], held=held_names)
    elif guard.kind == "lockset":
        if write:
            _eraser(fs, {lk.name for lk in held}, tname, cls, field,
                    "lockset")
    elif guard.kind == "immutable":
        if write:
            _record("immutable-write",
                    ("immutable", cls, field),
                    cls=cls, field=field, guard="immutable",
                    held=held_names)


def _eraser(fs: dict, held_names: Set[str], tname: str, cls: str,
            field: str, guard: str) -> None:  # holds: _state_lock
    """Eraser state machine over writes: candidate lockset = intersection
    of lock sets held at every write; empty with >1 writer thread =
    violation. Runs under ``_state_lock`` — the candidate set is shared
    across the threads being watched."""
    fs["writers"].add(tname)
    if fs["cand"] is None:
        fs["cand"] = set(held_names)
    else:
        fs["cand"] &= held_names
    if len(fs["writers"]) > 1 and not fs["cand"]:
        _record("eraser-lockset",
                ("eraser", cls, field),
                cls=cls, field=field, guard=guard,
                access="write", held=tuple(sorted(held_names)),
                writers=sorted(fs["writers"]),
                lockset=[])


_TRACED_CACHE: Dict[Tuple, type] = {}


def _traced_class(base: type, guards: Dict[str, Guard],
                  cache_key: Tuple) -> type:
    cached = _TRACED_CACHE.get(cache_key)
    if cached is not None:
        return cached
    # fields needing read tracing vs write tracing
    read_fields = {f: g for f, g in guards.items()
                   if g.kind in ("lock", "owner")}
    write_fields = {f: g for f, g in guards.items()
                    if g.kind in ("lock", "writelock", "owner", "lockset",
                                  "immutable")}

    class Traced(base):
        __tsan_base__ = base

        def __getattribute__(self, name):
            g = read_fields.get(name)
            if g is not None and _ACTIVE:
                _on_access(self, name, g, write=False)
            return object.__getattribute__(self, name)

        def __setattr__(self, name, value):
            g = write_fields.get(name)
            if g is not None and _ACTIVE:
                _on_access(self, name, g, write=True)
            object.__setattr__(self, name, value)

    Traced.__name__ = base.__name__
    Traced.__qualname__ = base.__qualname__
    Traced.__module__ = base.__module__
    _TRACED_CACHE[cache_key] = Traced
    return Traced


def instrument(obj, guards: Optional[Dict[str, str]] = None):
    """Instrument one instance: wrap its lock fields in
    :class:`TracedLock` and swap in the traced subclass. ``guards``
    overrides the schema (tests); by default the MRO-merged
    ``CONCURRENCY_SCHEMA`` entry applies. No-op if already traced or the
    class has no schema entry."""
    base = type(obj)
    if getattr(base, "__tsan_base__", None) is not None:
        return obj
    if guards is not None:
        parsed = {f: parse_guard(v) for f, v in guards.items()}
        cache_key = (base, tuple(sorted(guards.items())))
    else:
        if not any(k.__name__ in CONCURRENCY_SCHEMA
                   for k in base.__mro__):
            return obj
        parsed = _mro_guards(base)
        cache_key = (base,)

    # wrap lock-valued fields (before the class swap: these setattrs must
    # not themselves be traced as writes)
    locks: Dict[str, TracedLock] = {}
    d = object.__getattribute__(obj, "__dict__")
    for attr, value in list(d.items()):
        if isinstance(value, _LOCK_TYPES):
            tl = TracedLock(value, f"{base.__name__}.{attr}")
            object.__setattr__(obj, attr, tl)
            locks[attr] = tl
        elif isinstance(value, TracedLock):
            locks[attr] = value
    object.__setattr__(obj, "__tsan__", {"locks": locks, "fields": {}})
    obj.__class__ = _traced_class(base, parsed, cache_key)
    return obj


def maybe_instrument(obj) -> None:
    """Construction hook the serving classes call at the end of
    ``__init__``: a no-op (one flag check) unless the sanitizer is on."""
    if _ACTIVE:
        instrument(obj)


# ---------------------------------------------------------------------------
# lifecycle / reporting
# ---------------------------------------------------------------------------


def enable() -> None:
    global _ACTIVE
    _ACTIVE = True


def disable() -> None:
    global _ACTIVE
    _ACTIVE = False


def enabled() -> bool:
    return _ACTIVE


def set_schedule(schedule) -> None:
    """Install (or clear, with ``None``) the seeded interleaving schedule
    whose ``yield_point(hook, lock_name)`` runs at every instrumented
    acquire/release (``faults.InterleaveSchedule``)."""
    global _SCHEDULE
    _SCHEDULE = schedule


def reset() -> None:
    with _state_lock:
        _VIOLATIONS.clear()
        _SEEN.clear()
        _ORDER.clear()


def violations() -> List[dict]:
    with _state_lock:
        return [dict(v) for v in _VIOLATIONS]


def check() -> None:
    """Raise :class:`TsanViolations` when any violation was recorded —
    the structured report that fails tests."""
    v = violations()
    if v:
        raise TsanViolations(v)


class _Session:
    def __init__(self):
        self.violations: List[dict] = []

    def refresh(self) -> List[dict]:
        self.violations = violations()
        return self.violations


class session:
    """``with tsan.session() as report:`` — enable + reset around a
    block; ``report.violations`` holds the structured findings at exit
    (the sanitizer is disabled again, instrumented objects go inert).
    ``schedule`` installs a seeded interleaving schedule for the block."""

    def __init__(self, schedule=None):
        self.schedule = schedule
        self.report = _Session()
        self._was_active = False

    def __enter__(self) -> _Session:
        self._was_active = _ACTIVE
        reset()
        enable()
        set_schedule(self.schedule)
        return self.report

    def __exit__(self, *exc):
        self.report.refresh()
        set_schedule(None)
        # restore, don't force-disable: a DBSP_TPU_TSAN=1 run must stay
        # armed after the first session-using test exits
        if not self._was_active:
            disable()
        return False


# ---------------------------------------------------------------------------
# smoke dryrun (tools/lint_all.py `concurrency` front)
# ---------------------------------------------------------------------------


def dryrun(seconds: float = 2.0) -> dict:
    """TSAN smoke: a small served host pipeline hammered from threads
    must come out race-clean, and a seeded unlocked write must be CAUGHT
    (non-vacuity). Raises on either failing; returns a summary dict."""
    import queue as _queue
    import time

    # NO global jax.config mutation here: tier-1 runs this in-process
    # (tests/test_concurrency.py) where flipping the platform would leak
    # into every later test; the CPU pin comes from the caller's
    # environment (conftest / lint_all's subprocess / __main__ below)
    import jax.numpy as jnp

    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.io.catalog import Catalog
    from dbsp_tpu.io.controller import Controller, ControllerConfig
    from dbsp_tpu.obs import PipelineObs
    from dbsp_tpu.operators import add_input_zset

    with session() as report:
        def build(c):
            s, h = add_input_zset(c, [jnp.int64], [jnp.int64])
            return h, s.integrate().output()

        handle, (h, out) = Runtime.init_circuit(1, build)
        catalog = Catalog()
        catalog.register_input("t", h, (jnp.int64, jnp.int64))
        catalog.register_output("v", out, ())
        obs = PipelineObs(name="tsan-dryrun")
        ctl = Controller(handle, catalog, ControllerConfig(
            min_batch_records=1, flush_interval_s=0.01))
        obs.attach_circuit(handle.circuit)
        obs.attach_controller(ctl)
        ctl.start()
        errors: "_queue.Queue" = _queue.Queue()
        stop = threading.Event()

        def pusher():
            i = 0
            while not stop.is_set():
                try:  # catalog rows are ((key..., val...), weight) pairs
                    ctl.push("t", [((i, 1), 1)])
                except Exception as e:  # noqa: BLE001
                    errors.put(e)
                    return
                i += 1
                time.sleep(0.002)

        def watcher():
            while not stop.is_set():
                try:
                    ctl.stats()
                    obs.watch()
                    obs.slo.status_dict()
                except Exception as e:  # noqa: BLE001
                    errors.put(e)
                    return
                time.sleep(0.003)

        threads = [threading.Thread(target=pusher),
                   threading.Thread(target=watcher),
                   threading.Thread(target=watcher)]
        for t in threads:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        steps = ctl.steps
        ctl.stop()
        if not errors.empty():
            raise RuntimeError(f"dryrun worker died: {errors.get()}")
        if steps == 0:
            raise RuntimeError(
                "dryrun circuit loop never stepped — the serving thread "
                "died (the sanitizer result would be vacuous)")
    clean = list(report.violations)

    # non-vacuity: a seeded unlocked write MUST be caught
    class Racy:
        def __init__(self):
            self.lock = threading.Lock()
            self.n = 0

    with session() as report2:
        r = instrument(Racy(), guards={"lock": "immutable",
                                       "n": "writelock(lock)"})
        with r.lock:
            r.n += 1   # guarded write: fine
        r.n += 1       # unguarded write: the seeded defect

    caught = [v for v in report2.violations
              if v["kind"] == "declared-guard" and v["field"] == "n"]
    if clean:
        raise TsanViolations(clean)
    if not caught:
        raise AssertionError(
            "tsan dryrun: the seeded unlocked write was NOT caught — "
            "the sanitizer has rotted")
    summary = {"clean_pipeline_violations": 0,
               "seeded_defect_caught": True}
    print(f"tsan dryrun: ok {summary}")
    return summary


if __name__ == "__main__":
    # standalone CLI: pin the platform via env BEFORE jax imports (own
    # process only — the in-process callers inherit their host's config)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    dryrun()
