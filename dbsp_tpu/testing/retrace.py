"""Runtime compilation-stability sentinel: compile counting + transfer
guard over the steady-state tick.

The runtime half of the retrace sanitizer (static half: ``tools/
check_retrace.py``; registry: ``dbsp_tpu.retrace``), the way
``testing/tsan.py`` is the runtime half of the concurrency sanitizer.
Inside a :func:`session` — or process-wide under
``DBSP_TPU_RETRACE_SENTINEL=1`` — every watched
:class:`~dbsp_tpu.compiled.compiler.CompiledHandle` is instrumented:

* a ``logging.Handler`` on JAX's compile logger records every program
  XLA compiles BY NAME (the ``Compiling <fn>`` debug record carries the
  jitted function's ``__name__`` — exactly the name
  ``retrace.RETRACE_SCHEMA`` keys on);
* the handle's program builders (``_make_step`` / ``_make_scan``) and
  cause annotations (``_note_cause``) are wrapped so every DECLARED
  compile opportunity is ledgered: a construction permits one compile of
  its program, a ``residency`` cause note permits one more (tier flips
  recompile through the structure-keyed jit cache without a new
  construction);
* ``handle._steady_guard`` is armed to ``"disallow"``: the jitted step /
  scan call runs under ``jax.transfer_guard("disallow")``, so an
  IMPLICIT device<->host transfer in the steady tick — the class
  ``tools/check_hotpath.py``'s syntactic pass cannot see — raises at the
  dispatch site with a stack. Explicit ``jax.device_put`` /
  ``jax.device_get`` (the tick-cursor re-upload on a discontinuity, the
  validation fetch) remain legal.

:func:`check` raises :class:`~dbsp_tpu.retrace.RetraceError` when any
program in ``retrace.SENTINEL_PROGRAMS`` compiled more times than the
ledger allows — an undeclared recompile (~12ms trace+compile on this
CPU, seconds over a tunneled TPU, PER OCCURRENCE in the steady state).
Violations are NOT waivable at runtime: fix the retrace or declare the
cause in the schema (``# retrace: ok`` only waives static findings).

Typical test shape::

    from dbsp_tpu.testing import retrace as sentinel

    with sentinel.session(ch) as report:
        ch.run_ticks(t0, n, ...)        # steady state, post-warmup
    assert report.undeclared() == []    # or sentinel.check() to raise

Counts for programs OUTSIDE the sentinel set (drains, copies, lifted
SPMD callables) are informational — bench.py's ``retrace`` detail block
reports them per declared cause so perf claims can state "zero
undeclared recompiles" as recorded evidence. Names that collide with
eagerly-dispatched jnp primitives (``maximum``) over-count there; the
hard gate only reads the distinctive step-path names.
"""

from __future__ import annotations

import logging
import os
import threading
from collections import Counter
from typing import Dict, List, Optional

from dbsp_tpu.retrace import (CAUSES, RETRACE_SCHEMA, RetraceError,
                              SENTINEL_PROGRAMS, validate_schema)

__all__ = [
    "enable", "disable", "enabled", "watch", "unwatch", "maybe_watch",
    "note_construction", "reset", "compile_counts", "session", "Report",
    "check", "dryrun",
]

#: loggers that emit the ``Compiling <fn>`` debug record (module moved
#: across JAX versions; hooking both is harmless)
_COMPILE_LOGGERS = ("jax._src.interpreters.pxla", "jax.interpreters.pxla")

_state_lock = threading.RLock()
_ACTIVE = os.environ.get("DBSP_TPU_RETRACE_SENTINEL", "0") not in ("", "0")
_COMPILES: Counter = Counter()        # program name -> observed compiles
_CONSTRUCTIONS: Counter = Counter()   # program name -> builder calls
_CAUSE_NOTES: Counter = Counter()     # flight cause -> notes on watched
_WATCHED: List = []                   # handles instrumented this session
_HANDLER: Optional[logging.Handler] = None
_SAVED_LEVELS: Dict[str, int] = {}
_SAVED_PROPAGATE: Dict[str, bool] = {}

#: every program name any schema entry declares (log filter)
_SCHEMA_NAMES = frozenset(p.split(".", 1)[1] for p in RETRACE_SCHEMA)


class _CompileLogHandler(logging.Handler):
    """Counts ``Compiling <fn>`` records for schema'd program names."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            if isinstance(record.msg, str) and \
                    record.msg.startswith("Compiling") and record.args:
                name = str(record.args[0])
                if name in _SCHEMA_NAMES:
                    with _state_lock:
                        _COMPILES[name] += 1
        except Exception:  # noqa: BLE001 — a log hook must never throw
            pass


def _hook_logs() -> None:
    global _HANDLER
    if _HANDLER is not None:
        return
    _HANDLER = _CompileLogHandler(level=logging.DEBUG)
    for lname in _COMPILE_LOGGERS:
        logger = logging.getLogger(lname)
        _SAVED_LEVELS[lname] = logger.level
        _SAVED_PROPAGATE[lname] = logger.propagate
        logger.setLevel(logging.DEBUG)
        # our handler is attached DIRECTLY; stop the debug flood from
        # also reaching ancestor handlers (stderr) while hooked
        logger.propagate = False
        logger.addHandler(_HANDLER)


def _unhook_logs() -> None:
    global _HANDLER
    if _HANDLER is None:
        return
    for lname in _COMPILE_LOGGERS:
        logger = logging.getLogger(lname)
        logger.removeHandler(_HANDLER)
        logger.setLevel(_SAVED_LEVELS.get(lname, logging.NOTSET))
        logger.propagate = _SAVED_PROPAGATE.get(lname, True)
    _SAVED_LEVELS.clear()
    _SAVED_PROPAGATE.clear()
    _HANDLER = None


def note_construction(name: str) -> None:
    """Ledger one declared compile opportunity for program ``name`` (the
    wrapped builders call this; tests seed synthetic ledgers with it)."""
    with _state_lock:
        _CONSTRUCTIONS[name] += 1


def watch(handle) -> None:
    """Instrument one CompiledHandle: wrap its program builders and cause
    notes into the ledger, arm the steady-state transfer guard.
    Idempotent."""
    if any(h is handle for h in _WATCHED):
        return
    validate_schema()
    _hook_logs()
    orig_step, orig_scan = handle._make_step, handle._make_scan
    orig_note = handle._note_cause
    scan_name = "_scan_body" if handle.mesh is None else "scan_fn"

    def make_step():
        note_construction("step_fn")
        return orig_step()

    def make_scan(n):
        note_construction(scan_name)
        return orig_scan(n)

    def note_cause(cause):
        with _state_lock:
            _CAUSE_NOTES[cause] += 1
        orig_note(cause)

    handle._make_step = make_step
    handle._make_scan = make_scan
    handle._note_cause = note_cause
    handle._steady_guard = "disallow"
    with _state_lock:
        _WATCHED.append(handle)


def unwatch(handle) -> None:
    """Remove the instrumentation ``watch`` installed (instance-attribute
    shadows) and disarm the transfer guard."""
    for attr in ("_make_step", "_make_scan", "_note_cause"):
        handle.__dict__.pop(attr, None)
    handle._steady_guard = None
    with _state_lock:
        for i, h in enumerate(_WATCHED):
            if h is handle:
                del _WATCHED[i]
                break


def maybe_watch(handle) -> None:
    """Construction hook ``compile_circuit`` calls: a no-op (one flag
    check) unless the sentinel is on."""
    if _ACTIVE:
        watch(handle)


def enable() -> None:
    global _ACTIVE
    _ACTIVE = True


def disable() -> None:
    global _ACTIVE
    _ACTIVE = False


def enabled() -> bool:
    return _ACTIVE


def reset() -> None:
    with _state_lock:
        _COMPILES.clear()
        _CONSTRUCTIONS.clear()
        _CAUSE_NOTES.clear()


def compile_counts() -> Dict[str, int]:
    """Observed compiles per schema'd program name (all programs, not
    just the hard-gated sentinel set)."""
    with _state_lock:
        return dict(_COMPILES)


class Report:
    """Point-in-time view of the ledger; :meth:`undeclared` is the gate."""

    def __init__(self):
        self.refresh()

    def refresh(self) -> "Report":
        with _state_lock:
            self.compiles = dict(_COMPILES)
            self.constructions = dict(_CONSTRUCTIONS)
            self.causes = dict(_CAUSE_NOTES)
        return self

    def allowance(self, name: str) -> int:
        """Declared compile opportunities for a sentinel program: one per
        builder call, plus one per ``residency`` cause note (tier flips
        re-specialize through the structure-keyed cache without a new
        construction)."""
        return self.constructions.get(name, 0) + \
            self.causes.get("residency", 0)

    def undeclared(self) -> List[str]:
        out = []
        for name in SENTINEL_PROGRAMS:
            seen = self.compiles.get(name, 0)
            allowed = self.allowance(name)
            if seen > allowed:
                out.append(
                    f"{name}: {seen} compile(s) observed, "
                    f"{allowed} declared (constructions="
                    f"{self.constructions.get(name, 0)}, residency notes="
                    f"{self.causes.get('residency', 0)}) — an undeclared "
                    "retrace in the steady state; causes noted: "
                    f"{sorted(self.causes)} (vocabulary: "
                    f"{sorted(CAUSES)})")
        return out

    def summary(self) -> dict:
        """The bench-detail block: per-program compile counts joined with
        their declared causes, plus the guard status."""
        self.refresh()
        programs = {}
        for prog, causes in sorted(RETRACE_SCHEMA.items()):
            name = prog.split(".", 1)[1]
            n = self.compiles.get(name, 0)
            if n or name in SENTINEL_PROGRAMS:
                programs[prog] = {"compiles": n,
                                  "declared_causes": sorted(causes)}
        return {
            "programs": programs,
            "cause_notes": dict(sorted(self.causes.items())),
            "undeclared": self.undeclared(),
            "transfer_guard": "disallow",
        }


def check() -> None:
    """Raise :class:`RetraceError` on any undeclared sentinel-program
    compile. NOT waivable: fix the retrace or declare the cause."""
    bad = Report().undeclared()
    if bad:
        raise RetraceError(
            f"{len(bad)} undeclared recompile(s):\n  " + "\n  ".join(bad))


class session:
    """``with retrace.session(ch, ...) as report:`` — hook the compile
    log, reset the ledger, instrument the given handles (guard armed) for
    the block; ``report`` reflects the ledger at exit. Handles compiled
    INSIDE the block are auto-watched (``maybe_watch`` runs at the end of
    ``compile_circuit``)."""

    def __init__(self, *handles):
        self.handles = list(handles)
        self.report = Report()
        self._was_active = False

    def __enter__(self) -> Report:
        self._was_active = _ACTIVE
        reset()
        enable()
        _hook_logs()
        for h in self.handles:
            watch(h)
        return self.report

    def __exit__(self, *exc):
        self.report.refresh()
        with _state_lock:
            watched = list(_WATCHED)
        for h in watched:
            unwatch(h)
        if not self._was_active:
            disable()
            _unhook_logs()
        return False


# ---------------------------------------------------------------------------
# smoke dryrun (tools/lint_all.py `retrace` front)
# ---------------------------------------------------------------------------


def dryrun(ticks: int = 8) -> dict:
    """Sentinel smoke: a small compiled pipeline's steady state must come
    out with zero undeclared recompiles under an armed transfer guard,
    and a seeded per-value retrace (python-valued tick burned in as a
    static) must be CAUGHT. Raises on either failing; returns a summary.

    NO global jax.config mutation here (tier-1 runs this in-process);
    the CPU pin comes from the caller's environment."""
    import jax
    import jax.numpy as jnp

    from dbsp_tpu.circuit import Runtime
    from dbsp_tpu.compiled import compile_circuit
    from dbsp_tpu.operators import add_input_zset

    def build(c):
        s, h = add_input_zset(c, [jnp.int64], [jnp.int64])
        return h, s.integrate().output()

    handle, (h, out) = Runtime.init_circuit(1, build)

    def gen_fn(tick):
        from dbsp_tpu.zset.batch import Batch
        keys = (jnp.reshape(tick % 7, (1,)).astype(jnp.int64),)
        vals = (jnp.ones((1,), jnp.int64),)
        w = jnp.ones((1,), jnp.int64)
        return {h: Batch(keys, vals, w, runs=(1,))}

    ch = compile_circuit(handle, gen_fn=gen_fn)
    with session(ch) as report:
        ch.run_ticks(0, ticks, validate_every=4)
        ch.validate()
    clean = report.undeclared()
    if clean:
        raise RetraceError("dryrun steady state not clean:\n  " +
                           "\n  ".join(clean))
    if report.compiles.get("step_fn", 0) == 0:
        raise AssertionError(
            "retrace dryrun: no step_fn compile observed — the compile-"
            "log hook has rotted (the clean result would be vacuous)")

    # non-vacuity: a seeded per-value retrace MUST be caught. tick rides
    # as a STATIC here — the python-branch anti-pattern R001/R002 exist
    # for: every distinct value is a fresh cache key, a compile per tick.
    def step_fn(state, tick):
        if tick % 2 == 0:          # python branch on the static tick
            return state + 1
        return state - 1

    seeded = jax.jit(step_fn, static_argnums=(1,))
    with session() as report2:
        note_construction("step_fn")   # ONE declared compile
        st = jnp.zeros((), jnp.int64)
        for t in range(3):             # three distinct static values
            st = seeded(st, t)
    caught = report2.undeclared()
    if not caught:
        raise AssertionError(
            "retrace dryrun: the seeded per-value retrace was NOT "
            "caught — the sentinel has rotted")
    summary = {"steady_undeclared": 0,
               "steady_step_compiles": report.compiles.get("step_fn", 0),
               "seeded_defect_caught": True}
    print(f"retrace dryrun: ok {summary}")
    return summary


if __name__ == "__main__":
    # standalone CLI: pin the platform via env BEFORE jax imports (own
    # process only — in-process callers inherit their host's config)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    dryrun()
