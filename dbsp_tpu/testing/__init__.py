"""``dbsp_tpu.testing`` — fault-injection and robustness harnesses.

:mod:`dbsp_tpu.testing.faults` is the deterministic fault harness behind
the durability acceptance tests: seeded SIGKILL-at-tick of a pipeline
subprocess, transport connect/read failure injection, slow-consumer
stalls, checkpoint corruption, and seeded interleaving schedules — see
README §Durability.

:mod:`dbsp_tpu.testing.tsan` is the runtime concurrency sanitizer
(``DBSP_TPU_TSAN=1``): instrumented locks + attribute tracing over the
classes registered in ``dbsp_tpu.concurrency.CONCURRENCY_SCHEMA``,
enforcing declared guards with Eraser-style lockset inference and
lock-order inversion detection — see README §Static analysis.

Attribute access is lazy (PEP 562): the serving modules import
``dbsp_tpu.testing.tsan`` at module top for their construction hooks,
and an eager ``faults`` import here would cycle back through
``dbsp_tpu.io.transport``.
"""

_FAULTS_EXPORTS = (
    "FaultPlan", "StallingOutputTransport", "corrupt_checkpoint",
    "read_deltas", "read_status", "run_child", "spawn_child",
    "transport_chaos", "wait_for_tick", "InterleaveSchedule",
)

__all__ = list(_FAULTS_EXPORTS) + ["faults", "tsan"]


def __getattr__(name):
    import importlib

    if name in ("faults", "tsan"):
        return importlib.import_module(f"dbsp_tpu.testing.{name}")
    if name in _FAULTS_EXPORTS:
        faults = importlib.import_module("dbsp_tpu.testing.faults")
        return getattr(faults, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
