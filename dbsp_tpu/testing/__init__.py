"""``dbsp_tpu.testing`` — fault-injection and robustness harnesses.

:mod:`dbsp_tpu.testing.faults` is the deterministic fault harness behind
the durability acceptance tests: seeded SIGKILL-at-tick of a pipeline
subprocess, transport connect/read failure injection, slow-consumer
stalls, and checkpoint corruption — see README §Durability.
"""

from dbsp_tpu.testing.faults import (FaultPlan, StallingOutputTransport,
                                     corrupt_checkpoint, read_deltas,
                                     read_status, run_child,
                                     spawn_child, transport_chaos,
                                     wait_for_tick)

__all__ = [
    "FaultPlan", "StallingOutputTransport", "corrupt_checkpoint",
    "read_deltas", "read_status", "run_child", "spawn_child",
    "transport_chaos", "wait_for_tick",
]
