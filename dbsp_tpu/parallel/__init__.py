from dbsp_tpu.parallel.mesh import WORKER_AXIS, make_mesh, replicated, worker_sharding

__all__ = ["WORKER_AXIS", "make_mesh", "replicated", "worker_sharding"]
