"""Device mesh construction for SPMD worker parallelism.

The analog of the reference's worker-thread pool (``circuit/runtime.rs:137``):
a worker here is a TPU core/chip in a 1-D ``jax.sharding.Mesh`` named
``"workers"``. Sharded state lives as arrays with a leading worker axis;
the exchange operator's all-to-all rides ICI (see parallel/exchange.py).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

WORKER_AXIS = "workers"

# version compat: shard_map graduated from jax.experimental to the jax
# top level; support both so multi-worker circuits run on either jax
try:
    from jax import shard_map  # noqa: F401  (jax >= 0.6)
except ImportError:  # pragma: no cover — depends on installed jax
    from jax.experimental.shard_map import shard_map  # noqa: F401


def make_mesh(workers: int) -> Mesh:
    devices = jax.devices()
    assert workers <= len(devices), (
        f"requested {workers} workers but only {len(devices)} devices are "
        "visible (use XLA_FLAGS=--xla_force_host_platform_device_count=N "
        "JAX_PLATFORMS=cpu for virtual-device testing)")
    return Mesh(np.asarray(devices[:workers]), (WORKER_AXIS,))


def worker_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for arrays with a leading [workers, ...] axis."""
    return NamedSharding(mesh, PartitionSpec(WORKER_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
