"""Lift single-worker batch kernels over the worker mesh.

The execution convention for circuit-integrated sharding (reference:
``operator/communication/shard.rs:35-101``): a sharded stream carries
:class:`~dbsp_tpu.zset.batch.Batch` pytrees whose arrays have a leading
``[W]`` worker axis laid out over the 1-D mesh. Every operator keeps its
single-worker kernel; when its input is sharded the kernel is wrapped in
``shard_map`` (one jit per (mesh, kernel, static-config)) so each worker
evaluates its slice independently — cross-worker movement happens ONLY in
the explicit exchange/gather operators, exactly like the reference where
every operator body is single-threaded and ``shard()`` owns communication.
"""

from __future__ import annotations

from functools import lru_cache

import jax

from dbsp_tpu.parallel.exchange import spmd
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch


def current_mesh():
    from dbsp_tpu.circuit.runtime import Runtime

    rt = Runtime.current()
    assert rt is not None and rt.mesh is not None, (
        "sharded batch encountered outside a multi-worker Runtime context "
        "(build/step circuits through Runtime.init_circuit)")
    return rt.mesh


@lru_cache(maxsize=1024)
def _lifted_jit(mesh, factory, statics):
    """One compiled SPMD callable per (mesh, kernel factory, static config).

    ``factory(*statics)`` must return the pure per-worker function; the
    factory itself is the stable cache identity (module-level function), so
    the lambda it builds is created once per distinct config. Bounded:
    ``statics`` can hold operator instances (lifted_op), and an unbounded
    cache would pin every operator ever built for process lifetime —
    eviction only costs a re-jit (backed by the persistent compile cache).
    """
    return jax.jit(spmd(mesh, factory(*statics)))


def lifted(factory, *statics):
    """Dispatcher for sharded operator kernels: returns the compiled SPMD
    callable for the current mesh."""
    return _lifted_jit(current_mesh(), factory, tuple(statics))


def worker_scalar(v, dtype=None):
    """Replicate a host scalar to a [W] device array so it can ride through
    a ``lifted`` call as a runtime argument: ``shard_map``'s
    ``P(workers)`` spec splits it to a per-worker [1] slice, and the spmd
    body's squeeze hands each worker a 0-d scalar. The alternative — a
    static argument — would recompile the SPMD program per value (e.g. one
    compile per child-clock iteration in nested operators)."""
    import jax.numpy as jnp

    return jnp.full((current_mesh().devices.size,), v, dtype)


def op_kernel(op):
    """Factory for instance-bound kernels: the operator instance is the
    (hashable, stable) static identity; its ``_inner`` is the pure body."""
    return op._inner


def lifted_op(op):
    """SPMD dispatch of an operator's ``_inner(batch...)`` kernel."""
    return lifted(op_kernel, op)


# -- per-worker bodies used by Batch's host-path methods --------------------


def _consolidate_factory():
    def body(b: Batch) -> Batch:
        # per-worker slice: same regime dispatch (skip/rank-fold/sort) as
        # the single-worker path — run metadata rides the pytree aux data
        # through shard_map
        from dbsp_tpu.zset.batch import consolidate_regime

        if b.sorted_runs == 1:
            return b
        return consolidate_regime(b)

    return body


def _merge_factory():
    def body(a: Batch, b: Batch) -> Batch:
        cols, w = kernels.merge_sorted_cols(a.cols, a.weights,
                                            b.cols, b.weights)
        nk = len(a.keys)
        return Batch(cols[:nk], cols[nk:], w, runs=(w.shape[-1],))

    return body


def lifted_consolidate(batch: Batch) -> Batch:
    return lifted(_consolidate_factory)(batch)


def lifted_merge(a: Batch, b: Batch) -> Batch:
    return lifted(_merge_factory)(a, b)
