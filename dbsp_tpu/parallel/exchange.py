"""Shard / exchange / gather: worker parallelism as XLA collectives.

Reference components replaced (SURVEY.md §2.7, §5):
  * ``shard()`` — key-hash repartition across workers
    (``operator/communication/shard.rs:89``);
  * ``Exchange`` — the N²-mailbox shared-memory fabric with atomic
    ready-counters (``operator/communication/exchange.rs:45``);
  * ``gather()`` — all-to-one collection (``communication/gather.rs:41``).

TPU-native design: a sharded Z-set is a :class:`Batch` whose arrays carry a
leading ``[W, cap_local]`` worker axis laid out over the 1-D device mesh
(parallel/mesh.py). ``exchange`` runs INSIDE the jitted SPMD step as a bucket
+ ``lax.all_to_all`` over ICI — the reference's mailbox handshakes, ready
callbacks, and sender/receiver operator split all disappear because the
compiler schedules communication/compute overlap, and its per-step barrier
semantics (shard.rs:80-88) are exactly SPMD program semantics.

Routing invariant: rows are routed by a hash of the FIRST key column, so all
rows sharing a full key land on one worker (equal full keys share the first
column) — the same contract the reference's shard() gives join/aggregate/
distinct. Dead rows route nowhere (weight 0, dropped scatter).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dbsp_tpu.parallel.mesh import WORKER_AXIS, worker_sharding
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch


# Process-wide exchange bucket-overflow detections, by site kind
# ("exchange" = a CExchange's static per-worker output capacity, "input" =
# a sharded CInput's per-worker share capacity). The compiled step runs
# optimistically: a skewed tick can route more rows to one worker than the
# static bucket holds, and the surplus would silently fall off the
# ``with_cap`` slice — the requirement check catches it at the next
# validation, the overflow-replay machinery re-runs the interval at grown
# capacity, and THIS counter (exported as
# ``dbsp_tpu_exchange_overflow_total{kind}``, mirrored in bench detail)
# makes each such save visible instead of silent.
EXCHANGE_OVERFLOW_COUNTS: dict = {}


def count_exchange_overflow(kind: str, n: int = 1) -> None:
    EXCHANGE_OVERFLOW_COUNTS[kind] = EXCHANGE_OVERFLOW_COUNTS.get(kind, 0) + n


def _hash_key(col: jnp.ndarray) -> jnp.ndarray:
    """splitmix64-style mix of the first key column (any int dtype)."""
    z = col.astype(jnp.uint64) * jnp.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = z ^ (z >> jnp.uint64(27))
    return z


def worker_of(col: jnp.ndarray, nworkers: int) -> jnp.ndarray:
    return (_hash_key(col) % jnp.uint64(nworkers)).astype(jnp.int32)


def _bucketize(batch: Batch, nworkers: int) -> Batch:
    """Scatter local rows into [W, cap] bins by key hash (dead rows dropped).

    Rows keep their relative order within a bin; bins are zero-padded with
    sentinel keys so each bin is a valid (unconsolidated) batch slice.
    """
    cap = batch.cap
    dest = jnp.where(batch.weights != 0,
                     worker_of(batch.keys[0], nworkers),
                     jnp.int32(nworkers))  # out-of-range -> dropped scatter
    onehot = dest[None, :] == jnp.arange(nworkers, dtype=jnp.int32)[:, None]
    rank_by_worker = jnp.cumsum(onehot, axis=1) - 1        # [W, cap]
    rank = jnp.take_along_axis(
        rank_by_worker, jnp.clip(dest, 0, nworkers - 1)[None, :], axis=0)[0]

    def scatter(col, fill):
        out = jnp.full((nworkers, cap), fill, col.dtype)
        return out.at[dest, rank].set(col, mode="drop")

    keys = tuple(scatter(c, kernels.sentinel_for(c.dtype)) for c in batch.keys)
    vals = tuple(scatter(c, kernels.sentinel_for(c.dtype)) for c in batch.vals)
    w = scatter(batch.weights, jnp.zeros((), batch.weights.dtype))
    return Batch(keys, vals, w)


# ---------------------------------------------------------------------------
# In-SPMD-context primitives (call inside shard_map; axis name = "workers")
# ---------------------------------------------------------------------------


def exchange_local(batch: Batch, nworkers: int) -> Batch:
    """Repartition the local batch by key hash; per-worker view.

    Local [cap] rows are bucketed into ``nworkers`` bins of the full local
    capacity (worst-case skew = all rows to one peer), all_to_all'd over ICI,
    and consolidated. Output capacity is ``nworkers * cap``; callers
    re-bucket outside the jit boundary when they care (spine insert does).
    """
    binned = _bucketize(batch, nworkers)

    def a2a(x):
        return lax.all_to_all(x, WORKER_AXIS, split_axis=0, concat_axis=0,
                              tiled=True).reshape(nworkers * batch.cap)

    nk = len(batch.keys)
    cols = tuple(a2a(c) for c in binned.cols)
    w = a2a(binned.weights)
    # a consolidated input arrives as nworkers sorted runs (each peer's bin
    # keeps its relative order, live-packed with a sentinel tail) — the
    # regime dispatch folds sorted merges instead of re-sorting
    runs = (batch.cap,) * nworkers if batch.sorted_runs == 1 else None
    return Batch(cols[:nk], cols[nk:], w, runs).consolidate()


def gather_local(batch: Batch) -> Batch:
    """All-gather + consolidate: every worker ends with the full union
    (the reference's gather targets one worker; replication is the SPMD
    equivalent and what output handles consume). The peer group is the
    mesh axis itself — no worker count to pass (or get wrong)."""
    def ag(x):
        return lax.all_gather(x, WORKER_AXIS, tiled=True)

    nk = len(batch.keys)
    cols = tuple(ag(c) for c in batch.cols)
    w = ag(batch.weights)
    # the gather stacks every worker's consolidated slice: W sorted runs
    # (W read off the gathered shape — no worker count to pass or get wrong)
    runs = None
    if batch.sorted_runs == 1 and w.shape[-1] % batch.cap == 0:
        runs = (batch.cap,) * (w.shape[-1] // batch.cap)
    return Batch(cols[:nk], cols[nk:], w, runs).consolidate()


# ---------------------------------------------------------------------------
# Host-level helpers (outside shard_map)
# ---------------------------------------------------------------------------


def spmd(mesh: Mesh, fn):
    """Lift a per-worker function over 1-D batches to [W, ...] sharded
    batches via shard_map (leading worker axis squeezed inside)."""
    from dbsp_tpu.parallel.mesh import shard_map

    def lifted(*args):
        def body(*local):
            sq = jax.tree.map(lambda a: a[0], local)
            out = fn(*sq)
            return jax.tree.map(lambda a: a[None], out)

        return shard_map(body, mesh=mesh, in_specs=P(WORKER_AXIS),
                         out_specs=P(WORKER_AXIS))(*args)

    return lifted


@partial(jax.jit, static_argnames=("nworkers",))
def _shard_kernel(batch: Batch, nworkers: int) -> Batch:
    return _bucketize(batch, nworkers)


@lru_cache(maxsize=None)
def _sharded_consolidate(mesh: Mesh):
    return jax.jit(spmd(mesh, lambda b: b.consolidate()))


def shard_batch(batch: Batch, mesh: Mesh) -> Batch:
    """Distribute a 1-D batch into the [W, cap_local] sharded layout by key
    hash (the input-handle -> sharded-circuit boundary), consolidated
    per-worker."""
    nworkers = mesh.devices.size
    binned = _shard_kernel(batch, nworkers)
    binned = jax.device_put(binned, worker_sharding(mesh))
    return _sharded_consolidate(mesh)(binned)


def unshard_batch(sharded: Batch) -> Batch:
    """Collapse a [W, cap_local] sharded batch to one consolidated 1-D batch
    on the host driver (output-handle boundary).

    Run metadata must be RE-derived: tree-mapping the reshape would carry
    the per-worker tag onto the flattened rows, where a 1-run sharded batch
    is really W stacked per-worker runs (which is exactly the tag that lets
    the consolidate fold merges instead of sorting)."""
    flat = jax.tree.map(lambda a: a.reshape(-1), sharded)
    runs = (sharded.cap,) * sharded.weights.shape[0] \
        if sharded.sorted_runs == 1 else None
    return flat.tagged(runs).consolidate()
