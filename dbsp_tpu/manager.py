"""Pipeline manager: a control-plane service for SQL pipelines.

Reference: ``crates/pipeline_manager`` — a REST API over a project DB that
compiles SQL programs and runs pipeline processes (main.rs:76-194,
compiler.rs, runner.rs). Differences by design: "compilation" here is
planning SQL onto a circuit in-process (no cargo build / subprocess chain),
pipelines run as in-process controllers each with their own embedded HTTP
server (the reference spawns binaries), and program storage is a JSON file
instead of Postgres — the REST surface is preserved:

  * programs are VERSIONED: an update whose code differs increments the
    version and resets compile status (db/mod.rs:436-468);
  * compile status is a state machine ``none -> pending -> compiling_sql ->
    success | sql_error`` driven by a background compiler thread working a
    queue (compiler.rs:59-84 ProjectStatus; the rust stages collapse — XLA
    is the analog and runs at pipeline start);
  * programs support update/delete, pipelines support delete, with the
    reference's conflict rules (outdated version -> 409, delete of a
    program in use -> 409, delete of a running pipeline -> 409)
    (main.rs:720-744 update, :846-869 delete, :1406 pipeline_delete).

Routes:
  GET  /programs                     list names
  GET  /programs/<name>              full descriptor (version/status/error)
  POST /programs                     create (or no-op if identical code)
  POST /programs/<name>              update (version bump on code change)
  POST /programs/<name>/compile      enqueue {"version": N} (409 if stale)
  DELETE /programs/<name>            (409 while a pipeline references it)
  GET  /pipelines, /pipelines/<name>
  GET  /pipelines/<name>/profile     operator attribution (?ticks=N measured)
  GET  /pipelines/<name>/lineage     row lineage (?view=&key=, obs/lineage.py)
  POST /pipelines                    deploy {"name", "program"}
  POST /pipelines/<name>/shutdown
  POST /pipelines/<name>/checkpoint  write one durable generation now
  DELETE /pipelines/<name>           (409 while running)
  GET  /pipelines/<name>/view/<view> snapshot read fanned out over the
                                     replica set (primary fallback)
  GET  /pipelines/<name>/replicas    replica freshness (staleness_s)
  POST /pipelines/<name>/replicas    scale the read tier {"count": N}
  DELETE /pipelines/<name>/replicas  stop every replica

Durability: with ``DBSP_TPU_CHECKPOINT_DIR`` set (or a per-pipeline
``checkpoint_dir`` config key), each pipeline checkpoints periodically
into its own generation store and deploys RESTORE the newest valid
generation (see ``dbsp_tpu.checkpoint`` and README §Durability);
``DBSP_TPU_RESTORE_STRICT=1`` refuses deploys whose restore fails instead
of starting fresh.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

import jax.numpy as jnp

from dbsp_tpu.testing.tsan import maybe_instrument as _tsan_hook

DTYPES = {"int32": jnp.int32, "int64": jnp.int64, "float32": jnp.float32}

def _build_fn(program: dict):
    """The circuit builder for a program dict — shared by the compiler
    service (validation) and pipeline deployment."""
    tables = program["tables"]
    views = program["sql"]

    def build(c):
        from dbsp_tpu.operators import add_input_zset
        from dbsp_tpu.sql import SqlContext

        ctx = SqlContext(c)
        handles = {}
        for tname, spec in tables.items():
            dts = [DTYPES[d] for d in spec["dtypes"]]
            nkeys = spec.get("key_columns", 1)
            s, h = add_input_zset(c, dts[:nkeys], dts[nkeys:])
            ctx.register_table(tname, s, spec["columns"])
            handles[tname] = (h, dts)
        outs = {}
        for vname, sql in views.items():
            # the integral IS the served view: its state is the view's live
            # cardinality (retractions consolidate), not input history, and
            # SQL clients have no window knob — waive the unbounded-
            # integrate lint rather than warn on every deploy
            outs[vname] = (ctx.query(sql).integrate()
                           .waive_lint("I002").output())
        return handles, outs

    return build


class Pipeline:
    """One deployed program: circuit + controller + embedded server.

    ``config`` is an optional declarative pipeline config (io/config.py —
    the reference's YAML ``PipelineConfig``, controller/config.rs:28-131):
    its ControllerConfig fields tune batching/backpressure and its
    inputs/outputs sections attach transports before the pipeline starts."""

    def __init__(self, name: str, program: dict, config: Optional[dict] = None):
        self.name = name
        self.program = program
        self.config = config
        self.status = "created"
        self.controller = None
        self.server = None
        self.port: Optional[int] = None
        self.error: Optional[str] = None
        self.mode: Optional[str] = None  # compiled | host (set at deploy)
        self.obs = None  # obs.PipelineObs (set at deploy)
        # when compiled mode was requested but the deploy fell back to the
        # host scheduler: the recorded reason (the fallback perf cliff must
        # be visible in deploy status, not buried in a counter)
        self.fallback_reason: Optional[str] = None
        # tick restored from a checkpoint at deploy (None = fresh start)
        self.restored_tick: Optional[int] = None
        # read replicas (dbsp_tpu/serving.py ReplicaServer): stateless
        # snapshot servers fed by this pipeline's changefeed; the manager
        # fans /pipelines/<name>/view/<view> reads out across them
        self.replicas: List = []
        self._fanout_rr = 0
        self._replica_gauge = None
        self._replica_breached: Dict[str, bool] = {}
        _tsan_hook(self)

    def compile_and_start(self, _allow_restore: bool = True) -> None:
        from dbsp_tpu.circuit import Runtime
        from dbsp_tpu.io import Catalog, CircuitServer, build_controller
        from dbsp_tpu.obs import PipelineObs
        from dbsp_tpu.profile import CPUProfiler

        self.status = "compiling"
        # the pipeline config's `slo` section configures this pipeline's
        # watchdog objectives (obs/slo.py); omitted = fallback-only SLOs.
        # Kept across the rebuild-after-failed-restore pass so the
        # recorded restore flight event/incident survives.
        if self.obs is None:
            self.obs = PipelineObs(name=self.name,
                                   slo=(self.config or {}).get("slo"))
        # "workers" was already an accepted pipeline-config key
        # (io/config.py known_sections) but never honored: deploy over an
        # SPMD worker mesh when requested so managed pipelines shard
        workers = int((self.config or {}).get("workers", 1))
        handle, (handles, outs) = Runtime.init_circuit(
            workers, _build_fn(self.program))
        # static-analysis gate (dbsp_tpu/analysis): ERROR findings abort
        # the deploy (AnalysisError surfaces as the pipeline's error);
        # WARNs are logged and counted on this pipeline's registry as
        # dbsp_tpu_analysis_findings_total{rule,severity}
        from dbsp_tpu.analysis import verify_circuit

        findings = verify_circuit(handle.circuit, workers=workers,
                                  registry=self.obs.registry)
        # opt-in lineage taps (obs/lineage.py): retain raw input-table
        # integrals so GET /lineage resolves output rows down to concrete
        # input rows on tables no trace covers directly
        from dbsp_tpu.obs import lineage as _lineage

        if _lineage.taps_env_enabled(self.config):
            _lineage.enable_taps(handle.circuit)
        catalog = Catalog()
        for tname, (h, dts) in handles.items():
            catalog.register_input(tname, h, tuple(dts))
        for vname, out in outs.items():
            catalog.register_output(vname, out, ())
        # Execution-mode selection (facade.rs:48,105: SQL pipelines run the
        # JIT backend when the plan supports it): attempt the compiled
        # driver — one XLA program per tick — and fall back to the
        # host-driven scheduler for circuits using operators without a
        # compiled equivalent. The chosen mode is part of describe().
        driver = handle
        self.mode = "host"
        if os.environ.get("DBSP_TPU_MANAGER_COMPILED", "1") != "0":
            from dbsp_tpu.compiled.driver import try_compiled_driver

            compiled = try_compiled_driver(handle,
                                           registry=self.obs.registry,
                                           verified=True,
                                           flight=self.obs.flight)
            if compiled is not None:
                driver = compiled
                self.mode = "compiled"
            else:
                fb = self.obs.flight.events(kinds=("fallback",))
                if fb:
                    self.fallback_reason = fb[-1].get("reason")
                    if fb[-1].get("detail"):
                        self.fallback_reason += f": {fb[-1]['detail']}"
        if self.mode == "compiled":
            from dbsp_tpu.profile import CompiledProfiler

            profiler = CompiledProfiler(driver)
            self.obs.attach_compiled(driver)
        else:
            profiler = CPUProfiler(handle.circuit)
            self.obs.attach_circuit(handle.circuit)
        cfgd = dict(self.config or {})
        env_dir = os.environ.get("DBSP_TPU_CHECKPOINT_DIR")
        if env_dir and not cfgd.get("checkpoint_dir"):
            # per-pipeline subdirectory under the fleet checkpoint root
            cfgd["checkpoint_dir"] = os.path.join(env_dir, self.name)
        self.controller = build_controller(driver, catalog, cfgd)
        self.obs.attach_controller(self.controller)
        if not self._restore_on_deploy(_allow_restore):
            # the failed restore may have mutated engine state before
            # raising — serving it as "fresh" would double-apply replayed
            # inputs. Tear the half-restored build down and rebuild from
            # scratch with restore disabled (the flight event, latched
            # fallback_reason, and obs survive the second pass).
            self.controller.stop()  # no-progress stop: writes nothing
            self.controller = None
            return self.compile_and_start(_allow_restore=False)
        self.server = CircuitServer(self.controller, profiler=profiler,
                                    obs=self.obs, findings=findings)
        self.server.start()
        self.port = self.server.port
        self.controller.start()
        self.status = "running"

    def _restore_on_deploy(self, allow_restore: bool = True) -> bool:
        """Recovery: when the pipeline's checkpoint directory holds
        generations, restore the newest valid one before serving. A
        corrupted CURRENT generation falls back to the previous one and
        records a ``restore`` flight event (one SLO-visible incident). A
        restore that fails outright records the event with its reason and
        latches ``fallback_reason``; unless ``DBSP_TPU_RESTORE_STRICT=1``
        (which refuses the deploy — durability-critical fleets prefer a
        dead pipeline over a silent state reset), the caller REBUILDS the
        engine and starts fresh (returns False: a failed restore can
        leave partially-applied state behind, unsafe to serve)."""
        from dbsp_tpu import checkpoint as ckpt

        path = self.controller.checkpoint_dir
        if not allow_restore or not path or not ckpt.exists(path):
            return True
        try:
            info = self.controller.restore_from()
        except Exception as e:  # noqa: BLE001 — surfaced + policy below
            reason = f"{type(e).__name__}: {e}"
            self.obs.flight.record("restore", ok=False, reason=reason[:300])
            if os.environ.get("DBSP_TPU_RESTORE_STRICT", "0") != "0":
                raise RuntimeError(
                    f"restore-on-deploy failed (strict mode): {reason}")
            self.fallback_reason = f"restore failed: {reason[:200]}"
            return False
        self.restored_tick = info["tick"]
        self.obs.flight.record(
            "restore", ok=True, tick=info["tick"],
            generation=info.get("generation"),
            fallback_from=info.get("fallback_from"),
            reason=(f"generation {info.get('fallback_from')} corrupt; "
                    f"restored {info.get('name')}"
                    if info.get("fallback_from") else None))
        return True

    # -- read replicas -------------------------------------------------------
    def add_replicas(self, n: int) -> List[dict]:
        """Start ``n`` stateless read replicas fed by this pipeline's
        changefeed (serving.ReplicaServer). Each replica long-polls the
        pipeline port's ``/changefeed`` per view and serves ``/view/<name>``
        from its own folded state — reads never touch the primary's step
        path. Returns the new replicas' status dicts."""
        if self.status != "running" or self.port is None:
            raise RuntimeError(f"pipeline {self.name} is not running")
        if not self.controller.read_plane.enabled:
            raise RuntimeError("read plane disabled (DBSP_TPU_READPLANE=0)")
        from dbsp_tpu.serving import ReplicaServer

        views = list(self.controller.catalog.outputs)
        started = []
        base = len(self.replicas)
        for i in range(int(n)):
            r = ReplicaServer(f"http://127.0.0.1:{self.port}", views,
                              name=f"{self.name}-r{base + i}",
                              e2e=self.controller.e2e)
            r.start()
            self.replicas.append(r)
            started.append(r.status())
        if self._replica_gauge is None and self.obs is not None:
            self._replica_gauge = self.obs.registry.gauge(
                "dbsp_tpu_read_replica_staleness_seconds",
                "Per-replica read staleness: 0 when caught up to the "
                "primary's published epoch, else seconds since the newest "
                "record the replica has applied.",
                labels=("replica",))
            # collector: refresh staleness gauges on every scrape so the
            # metric is live without a poller thread
            def _collect() -> None:
                self.replica_status()

            self.obs.registry.register_collector(_collect)
        return started

    def replica_status(self) -> List[dict]:
        """Per-replica freshness: staleness is 0.0 while the replica's
        changefeed cursor has caught up to every view's published epoch on
        the primary, else seconds since the newest record it applied. A
        staleness breach (> ``DBSP_TPU_READ_STALENESS_BOUND_S``, default
        5.0 s) records one ``readpath`` flight event per transition."""
        plane = self.controller.read_plane if self.controller else None
        ps = plane.stats() if plane and plane.enabled else {}
        primary = ps.get("views", {})
        bound = float(os.environ.get(
            "DBSP_TPU_READ_STALENESS_BOUND_S", "5.0"))
        now = time.time()
        out = []
        for r in self.replicas:
            st = r.status()
            lag = 0.0
            for v, cur in st["epochs"].items():
                pe = (primary.get(v) or {}).get("epoch", 0)
                if cur < pe:
                    # behind: staleness since the newest record applied
                    # (never applied anything -> since the primary's last
                    # publish — the oldest data it could be missing)
                    ats = st["applied_ts"].get(v) \
                        or ps.get("last_publish_ts") or now
                    lag = max(lag, now - ats)
            st["staleness_s"] = lag
            if self._replica_gauge is not None:
                self._replica_gauge.labels(replica=st["name"]).set(lag)
            breached = lag > bound
            if breached and not self._replica_breached.get(st["name"]):
                if self.obs is not None:
                    self.obs.flight.record(
                        "readpath", replica=st["name"], staleness_s=lag,
                        bound_s=bound, stalled=st["stalled"])
            self._replica_breached[st["name"]] = breached
            out.append(st)
        return out

    def fanout_read(self, view: str, query: str = "") -> dict:
        """Route one read across the replica set round-robin; with no
        replicas (or a replica error) fall back to the primary's
        ``/view/<view>`` route. Reads never block ingest either way."""
        from urllib.parse import parse_qs

        t0 = time.perf_counter()
        reps = list(self.replicas)
        if reps:
            r = reps[self._fanout_rr % len(reps)]
            self._fanout_rr += 1
            try:
                ans = r.answer(view, parse_qs(query))
                plane = self.controller.read_plane
                if plane.enabled:
                    plane.note_read("replica_fanout", t0)
                return ans
            except Exception:  # noqa: BLE001 — replica down: use primary
                pass
        url = f"http://127.0.0.1:{self.port}/view/{view}"
        if query:
            url += f"?{query}"
        with urllib.request.urlopen(url, timeout=10.0) as resp:
            return json.loads(resp.read())

    def stop(self) -> None:
        for r in self.replicas:
            r.stop()
        self.replicas = []
        if self.controller:
            self.controller.stop()
        if self.server:
            self.server.stop()
        if self.status != "failed":
            self.status = "shutdown"

    def health(self) -> str:
        """SLO health of this pipeline: ok | degraded | unhealthy (plus
        the lifecycle states failed/shutdown when it is not running)."""
        if self.status == "failed":
            return "unhealthy"
        if self.status != "running" or self.obs is None:
            return "ok" if self.status in ("created", "compiling") \
                else "shutdown"
        try:
            self.obs.watch()  # fresh SLO evaluation (cheap, incremental)
            return self.obs.slo.status()
        except Exception:  # noqa: BLE001 — health polling is best-effort
            return "unknown"

    def describe(self) -> dict:
        out = {"name": self.name, "status": self.status, "port": self.port,
               "error": self.error, "mode": self.mode,
               "fallback_reason": self.fallback_reason,
               "restored_tick": self.restored_tick,
               "last_checkpoint_tick": getattr(
                   self.controller, "last_checkpoint_tick", None),
               "program_version": self.program.get("version")}
        out["health"] = self.health()
        if self.obs is not None:
            s = self.obs.slo.status_dict()
            out["slo"] = {"status": s["status"], "active": s["active"],
                          "incidents": s["incidents"],
                          "last_incident": s["last_incident"]}
        return out


class _CompilerService:
    """Background compile queue (compiler.rs): validates a program version
    by PLANNING its SQL onto a throwaway circuit; status transitions are
    observable through the program descriptor while it works."""

    def __init__(self, mgr: "PipelineManager"):
        self.mgr = mgr
        self.q: "queue.Queue" = queue.Queue()
        self.thread = threading.Thread(target=self._work, daemon=True,
                                       name="compiler-service")
        _tsan_hook(self)
        self.thread.start()

    def submit(self, name: str, version: int) -> None:
        self.q.put((name, version))

    def _work(self) -> None:
        from dbsp_tpu.circuit import Runtime

        while True:
            item = self.q.get()
            if item is None:
                return
            name, version = item
            with self.mgr.lock:
                prog = self.mgr.programs.get(name)
                # stale request: the program changed (or vanished) since
                # the compile was enqueued — drop it (compiler.rs picks the
                # latest version off the queue the same way)
                if prog is None or prog["version"] != version:
                    continue
                prog["status"] = "compiling_sql"
            try:
                Runtime.init_circuit(1, _build_fn(prog))
                status, error = "success", None
            except Exception as e:  # noqa: BLE001 — surface as sql_error
                status, error = "sql_error", f"{type(e).__name__}: {e}"
            with self.mgr.lock:
                prog = self.mgr.programs.get(name)
                if prog is not None and prog["version"] == version:
                    prog["status"] = status
                    prog["error"] = error
                    self.mgr._persist()

    def stop(self) -> None:
        self.q.put(None)


class PipelineManager:
    """REST service: /programs and /pipelines CRUD + compile lifecycle."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 storage_path: Optional[str] = None):
        self.programs: Dict[str, dict] = {}
        self.pipelines: Dict[str, Pipeline] = {}
        self.storage_path = storage_path
        self.lock = threading.RLock()
        if storage_path and os.path.exists(storage_path):
            with open(storage_path) as f:
                self.programs = json.load(f)
            for prog in self.programs.values():  # pre-lifecycle files
                prog.setdefault("version", 1)
                prog.setdefault("status", "none")
                prog.setdefault("error", None)
                if prog["status"] in ("pending", "compiling_sql"):
                    prog["status"] = "none"  # compile died with the process
        self.compiler = _CompilerService(self)
        mgr = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def do_GET(self):
                from urllib.parse import parse_qs, urlparse

                url = urlparse(self.path)
                parts = url.path.rstrip("/").split("/")
                if len(parts) == 5 and parts[1] == "pipelines" and \
                        parts[3] == "view":
                    # fan one snapshot read out across the pipeline's
                    # replica set (round-robin; primary fallback). Lock
                    # only for the lookup — the read itself never holds
                    # the manager lock nor any pipeline step lock
                    with mgr.lock:
                        p = mgr.pipelines.get(parts[2])
                    if p is None or p.status != "running":
                        return self._json({"error": "not found"}, 404)
                    try:
                        return self._json(p.fanout_read(parts[4],
                                                        url.query))
                    except KeyError as e:
                        return self._json(
                            {"error": f"unknown view {e}"}, 404)
                    except Exception as e:  # noqa: BLE001 — API error
                        return self._json(
                            {"error": f"{type(e).__name__}: {e}"}, 400)
                if len(parts) == 4 and parts[1] == "pipelines" and \
                        parts[3] == "replicas":
                    with mgr.lock:
                        p = mgr.pipelines.get(parts[2])
                    if p is None:
                        return self._json({"error": "not found"}, 404)
                    return self._json({"replicas": p.replica_status()})
                if len(parts) == 4 and parts[1] == "pipelines" and \
                        parts[3] == "lineage":
                    # row-level lineage for one deployed pipeline —
                    # proxied to its embedded server's quiesced slicer
                    # through the SAME query handler the pipeline port
                    # uses (obs/lineage.py http_query: view/key/n/dot
                    # parsing cannot drift between the two surfaces)
                    from dbsp_tpu.obs import lineage as _lineage

                    with mgr.lock:
                        p = mgr.pipelines.get(parts[2])
                    if p is None or p.server is None:
                        return self._json({"error": "not found"}, 404)
                    code, payload, dot = _lineage.http_query(
                        p.server.lineage_report, parse_qs(url.query))
                    if dot:
                        body = payload.encode()
                        self.send_response(code)
                        self.send_header("Content-Type",
                                         "text/vnd.graphviz")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    return self._json(payload, code)
                if len(parts) == 4 and parts[1] == "pipelines" and \
                        parts[3] in ("timeline", "spikes"):
                    # per-pipeline timeline / EXPLAIN SPIKE — proxied to
                    # the embedded server's quiesce-free readers (the
                    # timeline has its own lock; no step lock on this path)
                    with mgr.lock:
                        p = mgr.pipelines.get(parts[2])
                    if p is None or p.obs is None:
                        return self._json({"error": "not found"}, 404)
                    qs = parse_qs(url.query)
                    limit = int(qs["n"][0]) if "n" in qs else None
                    try:
                        p.obs.watch()
                        if parts[3] == "timeline":
                            since = int(qs["since"][0]) \
                                if "since" in qs else 0
                            view = qs["view"][0] if "view" in qs else None
                            return self._json(p.obs.timeline.to_dict(
                                since=since, view=view, limit=limit))
                        return self._json(
                            p.obs.timeline.explain_spikes(limit=limit))
                    except Exception as e:  # noqa: BLE001 — API error
                        return self._json(
                            {"error": f"{type(e).__name__}: {e}"}, 500)
                if len(parts) == 4 and parts[1] == "pipelines" and \
                        parts[3] == "profile":
                    # operator attribution for one deployed pipeline —
                    # proxied to its embedded server's quiesced report
                    # (in-process: same CircuitServer.profile_report the
                    # pipeline port serves at /profile)
                    with mgr.lock:
                        p = mgr.pipelines.get(parts[2])
                    if p is None or p.server is None:
                        return self._json({"error": "not found"}, 404)
                    qs = parse_qs(url.query)
                    ticks = int(qs["ticks"][0]) if "ticks" in qs else None
                    try:
                        return self._json(p.server.profile_report(
                            ticks=ticks))
                    except Exception as e:  # noqa: BLE001 — API error
                        return self._json(
                            {"error": f"{type(e).__name__}: {e}"}, 500)
                if self.path in ("/", ""):
                    from dbsp_tpu.console import CONSOLE_HTML

                    body = CONSOLE_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.rstrip("/") == "/metrics":
                    # fleet-wide Prometheus exposition: every deployed
                    # pipeline's registry under a pipeline="<name>" label
                    # (one scrape target for the whole manager)
                    from dbsp_tpu.obs import prometheus_text_many
                    from dbsp_tpu.obs.export import CONTENT_TYPE

                    with mgr.lock:
                        regs = [({"pipeline": p.name}, p.obs.registry)
                                for p in mgr.pipelines.values()
                                if p.obs is not None]
                    body = prometheus_text_many(regs).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.rstrip("/") == "/fleet/trace":
                    # one Perfetto-loadable fleet trace: every deployed
                    # pipeline's span ring plus every replica's, merged on
                    # their real pid lanes (per-process M metadata names
                    # the lanes; e2e spans correlate via trace ids)
                    from dbsp_tpu.obs.tracing import merge_chrome_traces

                    with mgr.lock:
                        pipes = list(mgr.pipelines.values())
                    traces = []
                    for p in pipes:
                        if p.obs is not None:
                            traces.append(p.obs.spans.to_chrome_trace())
                        for r in list(p.replicas):
                            traces.append(r.spans.to_chrome_trace())
                    self._json(merge_chrome_traces(traces))
                elif self.path.rstrip("/") == "/health":
                    self._json(mgr.fleet_health())
                elif self.path.rstrip("/") == "/programs":
                    with mgr.lock:
                        self._json(sorted(mgr.programs))
                elif len(parts) == 3 and parts[1] == "programs":
                    with mgr.lock:
                        prog = mgr.programs.get(parts[2])
                        if prog is None:
                            return self._json({"error": "not found"}, 404)
                        self._json(mgr._describe_program(prog))
                elif self.path.rstrip("/") == "/pipelines":
                    with mgr.lock:
                        self._json([p.describe()
                                    for p in mgr.pipelines.values()])
                elif len(parts) == 3 and parts[1] == "pipelines":
                    with mgr.lock:
                        p = mgr.pipelines.get(parts[2])
                    if p is None:
                        self._json({"error": "not found"}, 404)
                    else:
                        self._json(p.describe())
                else:
                    self._json({"error": "no route"}, 404)

            def do_POST(self):
                parts = self.path.rstrip("/").split("/")
                try:
                    if self.path.rstrip("/") == "/programs":
                        body = self._body()
                        self._json(mgr.upsert_program(body["name"], body))
                    elif len(parts) == 3 and parts[1] == "programs":
                        body = self._body()
                        out = mgr.upsert_program(parts[2], body,
                                                 update_only=True)
                        if out is None:
                            return self._json({"error": "not found"}, 404)
                        self._json(out)
                    elif len(parts) == 4 and parts[1] == "programs" \
                            and parts[3] == "compile":
                        body = self._body()
                        out, code = mgr.compile_program(
                            parts[2], body.get("version"))
                        self._json(out, code)
                    elif self.path.rstrip("/") == "/pipelines":
                        body = self._body()
                        name = body["name"]
                        # reserve the slot UNDER THE LOCK before the (slow)
                        # compile: delete_program's in-use check and
                        # delete_pipeline must see mid-deploy pipelines
                        with mgr.lock:
                            prev = mgr.pipelines.get(name)
                            if prev is not None and prev.status in (
                                    "created", "compiling", "running"):
                                return self._json(
                                    {"error": f"pipeline {name} already "
                                              f"{prev.status}"}, 409)
                            prog = mgr.programs[body["program"]]
                            p = Pipeline(name, prog,
                                         config=body.get("config"))
                            mgr.pipelines[name] = p
                        try:
                            p.compile_and_start()
                        except Exception as e:
                            p.error = f"{type(e).__name__}: {e}"
                            p.status = "failed"
                            p.stop()  # release partially started parts
                            return self._json({"error": p.error}, 400)
                        self._json(p.describe())
                    elif len(parts) == 4 and parts[1] == "pipelines" and \
                            parts[3] == "shutdown":
                        # look up under the lock (a concurrent DELETE
                        # mutates the dict); stop() itself runs outside
                        # it — it joins the circuit thread, and holding
                        # the manager lock for that would stall every
                        # other route for up to the join timeout
                        with mgr.lock:
                            p = mgr.pipelines.get(parts[2])
                        if p is None:
                            return self._json({"error": "not found"}, 404)
                        p.stop()
                        self._json(p.describe())
                    elif len(parts) == 4 and parts[1] == "pipelines" and \
                            parts[3] == "replicas":
                        # scale the read-serving tier: {"count": N} starts
                        # N changefeed-fed snapshot replicas
                        body = self._body()
                        with mgr.lock:
                            p = mgr.pipelines.get(parts[2])
                        if p is None:
                            return self._json({"error": "not found"}, 404)
                        started = p.add_replicas(int(body.get("count", 1)))
                        self._json({"replicas": started,
                                    "total": len(p.replicas)})
                    elif len(parts) == 4 and parts[1] == "pipelines" and \
                            parts[3] == "checkpoint":
                        with mgr.lock:
                            p = mgr.pipelines.get(parts[2])
                        if p is None or p.controller is None:
                            return self._json({"error": "not found"}, 404)
                        self._json(p.controller.checkpoint())
                    else:
                        self._json({"error": "no route"}, 404)
                except Exception as e:  # surface as API error, keep serving
                    self._json({"error": f"{type(e).__name__}: {e}"}, 400)

            def do_DELETE(self):
                parts = self.path.rstrip("/").split("/")
                try:
                    if len(parts) == 3 and parts[1] == "programs":
                        out, code = mgr.delete_program(parts[2])
                        self._json(out, code)
                    elif len(parts) == 4 and parts[1] == "pipelines" and \
                            parts[3] == "replicas":
                        # tear the replica tier down (lookup under the
                        # lock; stop() joins feed threads outside it)
                        with mgr.lock:
                            p = mgr.pipelines.get(parts[2])
                        if p is None:
                            return self._json({"error": "not found"}, 404)
                        reps, p.replicas = p.replicas, []
                        p._replica_breached.clear()
                        for r in reps:
                            r.stop()
                        self._json({"stopped": len(reps)})
                    elif len(parts) == 3 and parts[1] == "pipelines":
                        out, code = mgr.delete_pipeline(parts[2])
                        self._json(out, code)
                    else:
                        self._json({"error": "no route"}, 404)
                except Exception as e:
                    self._json({"error": f"{type(e).__name__}: {e}"}, 400)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        _tsan_hook(self)

    # -- program lifecycle ---------------------------------------------------
    @staticmethod
    def _describe_program(prog: dict) -> dict:
        return {"name": prog["name"], "version": prog["version"],
                "status": prog["status"], "error": prog.get("error"),
                "description": prog.get("description", "")}

    @staticmethod
    def _code_of(body: dict) -> dict:
        return {"tables": body.get("tables"), "sql": body.get("sql")}

    def upsert_program(self, name: str, body: dict,
                       update_only: bool = False) -> Optional[dict]:
        """Create, or update-with-version-bump when the CODE changed
        (db/mod.rs:436-468: description-only edits keep the version).

        ``update_only`` makes a missing program return None instead of
        creating it — the existence check belongs under this lock (a bare
        route-level check raced concurrent DELETEs, silently turning an
        update into a create)."""
        with self.lock:
            prev = self.programs.get(name)
            if prev is None:
                if update_only:
                    return None
                prog = dict(body, name=name, version=1, status="none",
                            error=None)
                self.programs[name] = prog
            elif self._code_of(prev) != self._code_of(body):
                prog = dict(body, name=name, version=prev["version"] + 1,
                            status="none", error=None)
                self.programs[name] = prog
            else:  # code identical: keep version + compile status
                prev["description"] = body.get(
                    "description", prev.get("description", ""))
                prog = prev
            self._persist()
            return self._describe_program(prog)

    def compile_program(self, name: str, version: Optional[int]):
        with self.lock:
            prog = self.programs.get(name)
            if prog is None:
                return {"error": "not found"}, 404
            if version is not None and version != prog["version"]:
                return {"error": f"outdated program version '{version}'"}, 409
            if prog["status"] in ("pending", "compiling_sql", "success"):
                return self._describe_program(prog), 200  # idempotent
            prog["status"] = "pending"
            prog["error"] = None
            self.compiler.submit(name, prog["version"])
            return self._describe_program(prog), 202

    def delete_program(self, name: str):
        with self.lock:
            if name not in self.programs:
                return {"error": "not found"}, 404
            used_by = [p.name for p in self.pipelines.values()
                       if p.program.get("name") == name
                       and p.status in ("created", "compiling", "running")]
            if used_by:
                return {"error": f"program {name} is used by active "
                                 f"pipelines: {used_by}"}, 409
            del self.programs[name]
            self._persist()
            return {"deleted": name}, 200

    def delete_pipeline(self, name: str):
        with self.lock:
            p = self.pipelines.get(name)
            if p is None:
                return {"error": "not found"}, 404
            if p.status in ("created", "compiling", "running"):
                return {"error": f"pipeline {name} is {p.status} — shut it "
                                 "down first"}, 409
            del self.pipelines[name]
            return {"deleted": name}, 200

    def fleet_health(self) -> dict:
        """Aggregate per-pipeline SLO status into one fleet state: the
        worst running pipeline wins (unhealthy > degraded > ok). Served at
        ``GET /health`` — the one poll a load balancer or pager needs."""
        rank = {"ok": 0, "shutdown": 0, "unknown": 1, "degraded": 1,
                "unhealthy": 2}
        with self.lock:
            pipes = list(self.pipelines.values())
        detail = {}
        worst_rank = 0
        for p in pipes:
            h = p.health()
            detail[p.name] = {"health": h, "status": p.status,
                              "mode": p.mode,
                              "fallback_reason": p.fallback_reason}
            worst_rank = max(worst_rank, rank.get(h, 1))
        worst = {0: "ok", 1: "degraded", 2: "unhealthy"}[worst_rank]
        return {"health": worst, "pipelines": detail}

    # -- persistence / serving -----------------------------------------------
    def _persist(self):  # holds: lock
        if self.storage_path:
            with open(self.storage_path, "w") as f:
                json.dump(self.programs, f)

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="manager")
        self._thread.start()

    def stop(self):
        # snapshot under the lock; stopping (which joins circuit threads)
        # happens outside it so in-flight routes are not stalled
        with self.lock:
            pipes = list(self.pipelines.values())
        for p in pipes:
            if p.status == "running":
                p.stop()
        self.compiler.stop()
        self.httpd.shutdown()
