"""Pipeline manager: a control-plane service for SQL pipelines.

Reference: ``crates/pipeline_manager`` — a REST API over a project DB that
compiles SQL programs and runs pipeline processes (main.rs:76-194,
compiler.rs, runner.rs). Differences by design: "compilation" here is
planning SQL onto a circuit in-process (no cargo build / subprocess chain),
pipelines run as in-process controllers each with their own embedded HTTP
server (the reference spawns binaries), and program storage is a JSON file
instead of Postgres — the REST surface (programs/pipelines CRUD, compile
status, start/stop, per-pipeline port discovery) is preserved.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import jax.numpy as jnp

DTYPES = {"int32": jnp.int32, "int64": jnp.int64, "float32": jnp.float32}


class Pipeline:
    """One deployed program: circuit + controller + embedded server."""

    def __init__(self, name: str, program: dict):
        self.name = name
        self.program = program
        self.status = "created"
        self.controller = None
        self.server = None
        self.port: Optional[int] = None
        self.error: Optional[str] = None

    def compile_and_start(self) -> None:
        from dbsp_tpu.circuit import Runtime
        from dbsp_tpu.io import Catalog, CircuitServer, Controller
        from dbsp_tpu.profile import CPUProfiler
        from dbsp_tpu.sql import SqlContext

        tables = self.program["tables"]
        views = self.program["sql"]

        def build(c):
            from dbsp_tpu.operators import add_input_zset

            ctx = SqlContext(c)
            handles = {}
            for tname, spec in tables.items():
                dts = [DTYPES[d] for d in spec["dtypes"]]
                nkeys = spec.get("key_columns", 1)
                s, h = add_input_zset(c, dts[:nkeys], dts[nkeys:])
                ctx.register_table(tname, s, spec["columns"])
                handles[tname] = (h, dts)
            outs = {}
            for vname, sql in views.items():
                outs[vname] = ctx.query(sql).integrate().output()
            return handles, outs

        self.status = "compiling"
        handle, (handles, outs) = Runtime.init_circuit(1, build)
        catalog = Catalog()
        for tname, (h, dts) in handles.items():
            catalog.register_input(tname, h, tuple(dts))
        for vname, out in outs.items():
            catalog.register_output(vname, out, ())
        profiler = CPUProfiler(handle.circuit)
        self.controller = Controller(handle, catalog)
        self.server = CircuitServer(self.controller, profiler=profiler)
        self.server.start()
        self.port = self.server.port
        self.controller.start()
        self.status = "running"

    def stop(self) -> None:
        if self.controller:
            self.controller.stop()
        if self.server:
            self.server.stop()
        if self.status != "failed":
            self.status = "shutdown"

    def describe(self) -> dict:
        return {"name": self.name, "status": self.status, "port": self.port,
                "error": self.error}


class PipelineManager:
    """REST service: /programs and /pipelines CRUD."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 storage_path: Optional[str] = None):
        self.programs: Dict[str, dict] = {}
        self.pipelines: Dict[str, Pipeline] = {}
        self.storage_path = storage_path
        if storage_path and os.path.exists(storage_path):
            with open(storage_path) as f:
                self.programs = json.load(f)
        mgr = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def do_GET(self):
                parts = self.path.rstrip("/").split("/")
                if self.path in ("/", ""):
                    from dbsp_tpu.console import CONSOLE_HTML

                    body = CONSOLE_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.rstrip("/") == "/programs":
                    self._json(sorted(mgr.programs))
                elif self.path.rstrip("/") == "/pipelines":
                    self._json([p.describe() for p in mgr.pipelines.values()])
                elif len(parts) == 3 and parts[1] == "pipelines":
                    p = mgr.pipelines.get(parts[2])
                    if p is None:
                        self._json({"error": "not found"}, 404)
                    else:
                        self._json(p.describe())
                else:
                    self._json({"error": "no route"}, 404)

            def do_POST(self):
                parts = self.path.rstrip("/").split("/")
                try:
                    if self.path.rstrip("/") == "/programs":
                        body = self._body()
                        mgr.programs[body["name"]] = body
                        mgr._persist()
                        self._json({"name": body["name"]})
                    elif self.path.rstrip("/") == "/pipelines":
                        body = self._body()
                        name = body["name"]
                        if name in mgr.pipelines and \
                                mgr.pipelines[name].status == "running":
                            return self._json(
                                {"error": f"pipeline {name} already running"},
                                409)
                        prog = mgr.programs[body["program"]]
                        p = Pipeline(name, prog)
                        try:
                            p.compile_and_start()
                        except Exception as e:
                            p.error = f"{type(e).__name__}: {e}"
                            p.status = "failed"
                            p.stop()  # release any partially started parts
                            mgr.pipelines[name] = p
                            return self._json({"error": p.error}, 400)
                        mgr.pipelines[name] = p
                        self._json(p.describe())
                    elif len(parts) == 4 and parts[1] == "pipelines" and \
                            parts[3] == "shutdown":
                        mgr.pipelines[parts[2]].stop()
                        self._json(mgr.pipelines[parts[2]].describe())
                    else:
                        self._json({"error": "no route"}, 404)
                except Exception as e:  # surface as API error, keep serving
                    self._json({"error": f"{type(e).__name__}: {e}"}, 400)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _persist(self):
        if self.storage_path:
            with open(self.storage_path, "w") as f:
                json.dump(self.programs, f)

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="manager")
        self._thread.start()

    def stop(self):
        for p in self.pipelines.values():
            if p.status == "running":
                p.stop()
        self.httpd.shutdown()
