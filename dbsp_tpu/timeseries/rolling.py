"""Partitioned rolling aggregates: per-key sliding-range aggregation.

Reference: ``operator/time_series/rolling_aggregate.rs:235``
(``partitioned_rolling_aggregate``) with ``RelRange`` semantics
(``time_series/range.rs``): for every input row (p, t, v) the output holds
(p, t) -> agg over p's rows with time in [t - range, t].

Incremental algorithm (the reference maintains a radix-tree time index for
O(log n) range sums; here round 1 recomputes each affected window —
SURVEY.md §7 stage 7 "start with O(window) recompute, optimize later"):

  1. a delta row (p, ts) dirties output rows (p, t') with t' ∈ [ts, ts+range]
     — find them with two-column (p, time) lex probes over the post trace,
     plus the delta rows themselves;
  2. recompute each dirty window [t'-range, t'] with the same probes +
     prefix-sum expansion + the aggregator's segment reduction;
  3. diff against the output spine (retract/insert), exactly like
     incremental aggregation.

Cost per tick: O(|delta| · rows-per-window · log |trace|) — delta-
proportional, state-independent.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dbsp_tpu.circuit.builder import CircuitError, Stream
from dbsp_tpu.circuit.operator import UnaryOperator
from dbsp_tpu.operators.aggregate import Aggregator, GroupGather, _TupleMax, \
    _diff_outputs, _reduce_groups
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.operators.trace_op import TraceView
from dbsp_tpu.trace.spine import Spine
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch, bucket_cap


def _range_gather_ladder_impl(qp, qlo, qhi, qlive, levels, out_cap: int):
    """Rows of the (p, time)-keyed ladder with key p==qp and time in
    [qlo, qhi], in ONE fused launch over ALL levels — the aggregate
    family's shared cursor entry point (cursor.gather_ladder) with
    distinct lo/hi probe columns and the time key column gathered back.
    Returns ((qrow ids, time col + val cols, weights), total); dead slots
    carry qrow == q_cap (the trash segment) and sentinel cols."""
    from dbsp_tpu.zset import cursor

    return cursor.gather_ladder((qp, qlo), qlive, levels, out_cap,
                                qhi_keys=(qp, qhi), gather_keys=1)


_range_gather_ladder = jax.jit(_range_gather_ladder_impl,
                               static_argnames=("out_cap",))


def _range_gather_ladder_factory(out_cap: int):
    return lambda qp, qlo, qhi, qlive, levels: _range_gather_ladder_impl(
        qp, qlo, qhi, qlive, levels, out_cap)


class RangeGather:
    """Host driver for per-row [lo, hi] time-range gathers: the full
    ladder in ONE fused launch through the same cursor entry point the
    equality aggregates use (one probe pair over the ladder, one
    cross-level expansion, one monotone shared capacity — the per-level
    loop paid K probe kernels and K grow-on-demand buffers). Sharded
    query sets gather per worker; the capacity check takes the worst
    worker."""

    def __init__(self):
        self.out_cap = 0  # fused ladder output capacity (monotone)

    @staticmethod
    def _launch(qp, qlo, qhi, qlive, levels, cap):
        if qlive.ndim > 1:  # sharded query set
            from dbsp_tpu.parallel.lift import lifted

            return lifted(_range_gather_ladder_factory, cap)(
                qp, qlo, qhi, qlive, levels)
        return _range_gather_ladder(qp, qlo, qhi, qlive, levels, cap)

    def __call__(self, qp, qlo, qhi, qlive, levels, q_cap):
        import numpy as np

        if not levels:
            return None
        levels = tuple(levels)
        if not self.out_cap:
            self.out_cap = bucket_cap(max(64, q_cap))
        part, total = self._launch(qp, qlo, qhi, qlive, levels,
                                   self.out_cap)
        t = int(np.max(jax.device_get(total)))  # ONE sync; worst worker
        if t > self.out_cap:
            self.out_cap = bucket_cap(t)
            part, _ = self._launch(qp, qlo, qhi, qlive, levels,
                                   self.out_cap)
        qrow, cols, w = part
        return qrow, cols[0], cols[1:], w


def _rolling_reduce_impl(wrow, wt, wvals, ww, at, agg: Aggregator,
                         a_cap: int):
    """Net gathered window rows (keeping the time column so distinct input
    rows never merge), reduce per dirty slot, and require a live row at the
    slot's own timestamp for the output to exist."""
    cols, cw = kernels.consolidate_cols((wrow, wt, *wvals), ww)
    wrow, wt, wvals = cols[0], cols[1], cols[2:]
    seg = jnp.where((wrow >= 0) & (wrow < a_cap), wrow,
                    a_cap).astype(jnp.int32)
    outs = agg.reduce(wvals, cw, seg, a_cap + 1)
    own_time = at[jnp.clip(wrow, 0, a_cap - 1)]
    self_live = (cw > 0) & (wt == own_time)
    present = jax.ops.segment_max(
        jnp.where(self_live, 1, 0), seg, num_segments=a_cap + 1)
    return tuple(o[:a_cap] for o in outs), present[:a_cap] > 0


_rolling_reduce_jit = jax.jit(_rolling_reduce_impl,
                              static_argnames=("agg", "a_cap"))


def _rolling_reduce_factory(agg: Aggregator, a_cap: int):
    return lambda wrow, wt, wvals, ww, at: _rolling_reduce_impl(
        wrow, wt, wvals, ww, at, agg, a_cap)


def _rolling_reduce(wrow, wt, wvals, ww, at, agg, a_cap):
    if ww.ndim > 1:  # sharded window parts
        from dbsp_tpu.parallel.lift import lifted

        return lifted(_rolling_reduce_factory, agg, a_cap)(
            wrow, wt, wvals, ww, at)
    return _rolling_reduce_jit(wrow, wt, wvals, ww, at, agg, a_cap)


def _dirty_rows_impl(dp, dt, dlive, qrow, t, w):
    """Dirty (p, t') slots: the delta's own rows plus the gathered affected
    rows, consolidated to distinct slots (presence weights)."""
    p_g = jnp.where(qrow >= 0, dp[jnp.clip(qrow, 0, dp.shape[0] - 1)],
                    kernels.sentinel_for(dp.dtype))
    p_all = jnp.concatenate([dp, p_g])
    t_all = jnp.concatenate([dt, t])
    keep = jnp.concatenate([dlive, (w != 0) & (qrow >= 0)])
    cols, cw = kernels.consolidate_cols(
        (p_all, t_all), jnp.where(keep, 1, 0).astype(jnp.int64))
    return cols[0], cols[1], cw != 0


_dirty_rows_jit = jax.jit(_dirty_rows_impl)


def _dirty_rows_factory():
    return _dirty_rows_impl


def _dirty_rows(dp, dt, dlive, qrow, t, w):
    if dlive.ndim > 1:
        from dbsp_tpu.parallel.lift import lifted

        return lifted(_dirty_rows_factory)(dp, dt, dlive, qrow, t, w)
    return _dirty_rows_jit(dp, dt, dlive, qrow, t, w)


def _dirty_delta_only_impl(dp, dt, dlive):
    cols, cw = kernels.consolidate_cols(
        (dp, dt), jnp.where(dlive, 1, 0).astype(jnp.int64))
    return cols[0], cols[1], cw != 0


_dirty_delta_only_jit = jax.jit(_dirty_delta_only_impl)


def _dirty_delta_only_factory():
    return _dirty_delta_only_impl


def _dirty_delta_only(dp, dt, dlive):
    if dlive.ndim > 1:
        from dbsp_tpu.parallel.lift import lifted

        return lifted(_dirty_delta_only_factory)(dp, dt, dlive)
    return _dirty_delta_only_jit(dp, dt, dlive)


class RollingAggregateOp(UnaryOperator):
    """Input: keys (partition, time), vals (value cols). Output: keys
    (partition, time), vals (agg outputs).

    When the aggregator has a combine semigroup (Max/Min/Sum/Count), dirty
    windows are answered by a :class:`RadixTimeIndex` in O(log range)
    gathered rows each; otherwise (Average, custom Folds) each window is
    recomputed from the trace in O(window rows) — the round-1 fallback.
    """

    def __init__(self, agg: Aggregator, range_ms: int, schema, name=None,
                 use_tree: bool = True):
        from dbsp_tpu.timeseries.radix_tree import (RadixTimeIndex,
                                                    combine_for)

        self.agg = agg
        self.range_ms = range_ms
        self.in_schema = schema
        self.out_schema = (tuple(schema[0]), tuple(agg.out_dtypes))
        self.name = name or f"rolling<{agg.name},{range_ms}>"
        self.out_spine = Spine(*self.out_schema)
        self._affected = RangeGather()
        self._windows = RangeGather()
        self._old = GroupGather()
        self.tree = None
        if use_tree and len(agg.out_dtypes) == 1 \
                and getattr(agg, "col", 0) == 0:
            try:
                combine_for(agg)
            except TypeError:
                pass
            else:
                self.tree = RadixTimeIndex(agg, schema[0][0], schema[0][1],
                                           max_time_range=range_ms)

    def clock_start(self, scope: int) -> None:
        if scope > 0:
            self.out_spine = Spine(*self.out_schema)
            if self.tree is not None:
                from dbsp_tpu.timeseries.radix_tree import RadixTimeIndex

                self.tree = RadixTimeIndex(
                    self.agg, self.in_schema[0][0], self.in_schema[0][1],
                    max_time_range=self.range_ms)

    def eval(self, view: TraceView) -> Batch:
        delta = view.delta
        if int(delta.live_count()) == 0:
            return Batch.empty(*self.out_schema,
                               lead=tuple(delta.weights.shape[:-1]))
        q_cap = delta.cap
        dp, dt = delta.keys[0], delta.keys[1]
        dlive = delta.weights != 0

        # 1. dirty (p, t') rows: trace rows in [ts, ts+range] per delta row,
        #    plus the delta rows themselves. Only keys/weights matter here —
        #    strip the value columns (free: pytree re-wrap, no copy) so the
        #    expansion doesn't gather payloads it immediately discards.
        key_only = [Batch(b.keys, (), b.weights) for b in view.spine.batches]
        gathered = self._affected(
            dp, dt, dt + self.range_ms, dlive, key_only, q_cap)
        if gathered is None:
            ap, at, alive = _dirty_delta_only(dp, dt, dlive)
        else:
            qrow, t, _, w = gathered
            ap, at, alive = _dirty_rows(dp, dt, dlive, qrow, t, w)
        a_cap = ap.shape[-1]

        # 2. recompute each dirty window [t'-range, t'] — via the radix tree
        # (O(log range) gathered rows per window) when available, else a
        # full-window gather. An output row (p, t') exists only while an
        # input row at exactly (p, t') is live — a non-empty window alone is
        # not enough (the retraction of (p, t') must retract its output even
        # though neighbours still populate the window).
        if self.tree is not None:
            self.tree.update(delta, view.spine.batches)
            new_vals, _range_present = self.tree.query(
                ap, at - self.range_ms, at, alive, view.spine.batches, a_cap)
            # presence requires a live row at exactly (p, t')
            own = self.tree.query(ap, at, at, alive, view.spine.batches,
                                  a_cap)
            new_present = own[1]
        else:
            win = self._windows(ap, at - self.range_ms, at, alive,
                                view.spine.batches, a_cap)
            if win is None:
                new_vals = tuple(jnp.zeros(alive.shape, d)
                                 for d in self.agg.out_dtypes)
                new_present = jnp.zeros(alive.shape, jnp.bool_)
            else:
                new_vals, new_present = _rolling_reduce(
                    win[0], win[1], win[2], win[3], at, self.agg, a_cap)

        # 3. diff vs previous outputs for the dirty keys
        old = self._old((ap, at), alive, self.out_spine.batches, a_cap)
        if old is None:
            old_vals = tuple(kernels.sentinel_fill(alive.shape, d)
                             for d in self.agg.out_dtypes)
            old_present = jnp.zeros(alive.shape, jnp.bool_)
        else:
            old_vals, old_present = _reduce_groups(
                tuple(old), _TupleMax(len(self.agg.out_dtypes)), a_cap,
                net=len(self.out_spine.batches) > 1)

        cols, w = _diff_outputs((ap, at), alive, new_vals, new_present,
                                old_vals, old_present)
        out = Batch(cols[:2], cols[2:], w).shrink_to_fit()
        self.out_spine.insert(out)
        return out

    def state_dict(self):
        state = {"out_spine": self.out_spine}
        if self.tree is not None:
            state["tree_levels"] = self.tree.levels
        return state

    def load_state_dict(self, state):
        self.out_spine = state["out_spine"]
        if self.tree is not None and "tree_levels" in state:
            self.tree.levels = state["tree_levels"]

    def metadata(self):
        meta = {"out_levels": len(self.out_spine.batches)}
        if self.tree is not None:
            meta["tree_levels"] = [len(s.batches) for s in self.tree.levels]
            meta["tree_query_rows"] = self.tree.query_rows_gathered
        return meta


@stream_method
def partitioned_rolling_aggregate(self: Stream, agg: Aggregator,
                                  range_ms: int, name=None,
                                  use_tree: bool = True) -> Stream:
    """Per-partition rolling aggregate over [t - range_ms, t] (see module
    doc). The stream must be keyed (partition, time). ``use_tree=False``
    forces the O(window) recompute path (the differential-testing oracle
    for the radix-tree path)."""
    from dbsp_tpu.operators.registry import require_schema

    schema = require_schema(self, "partitioned_rolling_aggregate")
    if len(schema[0]) != 2:
        raise CircuitError(
            "partitioned_rolling_aggregate needs keys (partition, time), "
            f"got {len(schema[0])} key column(s)")
    # sharded streams stay sharded: rows route by the partition column, so
    # every partition's window lives wholly on one worker and per-worker
    # rolling unions exactly (reference: rolling_aggregate.rs:235
    # self-shards by partition the same way). The radix-tree fast path is
    # shard-lifted too — per-worker trees over the partition key-slices
    # (timeseries/radix_tree.py module doc), so use_tree keeps its meaning
    # at any worker count.
    t = self.trace()
    out = self.circuit.add_unary_operator(
        RollingAggregateOp(agg, range_ms, schema, name, use_tree=use_tree), t)
    out.schema = (tuple(schema[0]), tuple(agg.out_dtypes))
    out.key_sharded = getattr(t, "key_sharded", False)
    return out
