"""Watermarks: lateness bounds over event-time columns.

Reference: ``operator/time_series/watermark.rs:33`` (``watermark_monotonic``):
given a monotone timestamp extraction, the watermark at tick t is
``max(event_time seen so far) - lateness`` — a host scalar stream used to
drive window bounds and trace GC.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from dbsp_tpu.circuit.builder import Stream
from dbsp_tpu.circuit.operator import UnaryOperator
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.zset.batch import Batch


@jax.jit
def _max_live(col: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
    lo = jnp.iinfo(col.dtype).min if jnp.issubdtype(col.dtype, jnp.integer) \
        else -jnp.inf
    return jnp.max(jnp.where(weights != 0, col, lo))


class WatermarkMonotonic(UnaryOperator):
    """Emits the running max of a timestamp column minus lateness.

    The reference requires the extracted timestamp to be monotone over
    *inserted* rows; we take the running max so late (but allowed) rows and
    retractions are tolerated — the watermark never regresses either way.
    """

    name = "watermark"

    def __init__(self, ts_fn: Callable[[Tuple, Tuple], jnp.ndarray],
                 lateness: int):
        self.ts_fn = ts_fn
        self.lateness = lateness
        self._wm = None
        self._max_ts = None        # running max event time (the frontier)
        self._last_batch_max = None  # latest batch's max (lag gauge; not
        #                              persisted — transient per process

    def clock_start(self, scope: int) -> None:
        self._wm = None
        self._max_ts = None
        self._last_batch_max = None

    def eval(self, batch: Batch) -> int:
        if int(batch.live_count()) > 0:
            m = int(_max_live(self.ts_fn(batch.keys, batch.vals),
                              batch.weights))
            self._last_batch_max = m
            self._max_ts = m if self._max_ts is None else max(self._max_ts, m)
            cand = m - self.lateness
            self._wm = cand if self._wm is None else max(self._wm, cand)
        return self._wm  # None until the first event arrives

    def metadata(self):
        return {"watermark": self._wm, "max_event_time": self._max_ts,
                "last_batch_max": self._last_batch_max}

    def state_dict(self):
        return {"wm": self._wm, "max_ts": self._max_ts}

    def load_state_dict(self, state):
        self._wm = state["wm"]
        self._max_ts = state.get("max_ts")
        self._last_batch_max = None


@stream_method
def watermark_monotonic(self: Stream, ts_fn, lateness: int = 0) -> Stream:
    """Host-scalar stream of the current watermark (or None pre-first-event)."""
    return self.circuit.add_unary_operator(
        WatermarkMonotonic(ts_fn, lateness), self)
