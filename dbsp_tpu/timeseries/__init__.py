from dbsp_tpu.timeseries import rolling, watermark, window  # noqa: F401  (register)
from dbsp_tpu.timeseries.watermark import WatermarkMonotonic
from dbsp_tpu.timeseries.window import WindowOp

__all__ = ["WatermarkMonotonic", "WindowOp"]
