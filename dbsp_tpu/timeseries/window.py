"""The window operator: retain rows whose (leading) key lies in moving bounds.

Reference: ``operator/time_series/window.rs:75-130`` — a ternary operator over
(bounds, delta, trace) emitting the Z-set delta of the window contents as the
bounds move monotonically.

Per tick, with previous bounds [a0, b0) and new bounds [a1, b1) (monotone:
a1 >= a0, b1 >= b0):

    out = Δin ∩ [a1, b1)                      (new rows inside the window)
        - trace_pre ∩ [a0, min(a1, b0))       (rows that slid out)
        + trace_pre ∩ [max(b0, a1), b1)       (rows that slid in)

Range extraction is a scalar searchsorted pair + masked slice per spine level
(grow-on-demand capacity) — O(log n + |range delta|), the same cost class the
reference gets from its trace cursors.

When ``gc=True`` the operator also truncates the shared trace below the new
lower bound (the reference's TraceBound lower-bound GC, operator/trace.rs:29),
which keeps state proportional to the window span.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from dbsp_tpu.circuit.builder import Stream
from dbsp_tpu.circuit.operator import BinaryOperator
from dbsp_tpu.operators.registry import stream_method
from dbsp_tpu.operators.trace_op import TraceView
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch, bucket_cap, concat_batches


def _slice_range_impl(level: Batch, a, b, out_cap: int):
    """Rows of a consolidated level with first-key in [a, b); masked slice."""
    k0 = level.keys[0]
    a = jnp.asarray(a, k0.dtype)
    b = jnp.asarray(b, k0.dtype)
    lo = jnp.searchsorted(k0, a, side="left")
    hi = jnp.searchsorted(k0, b, side="left")
    total = hi - lo
    j = jnp.arange(out_cap)
    idx = jnp.clip(lo + j, 0, level.cap - 1)
    valid = j < total
    cols = tuple(
        jnp.where(valid, c[idx], kernels.sentinel_for(c.dtype))
        for c in level.cols)
    w = jnp.where(valid, level.weights[idx], 0)
    nk = len(level.keys)
    # a contiguous slice of a consolidated level, re-packed at the front
    # with a sentinel tail, is itself one consolidated run
    return Batch(cols[:nk], cols[nk:], w, runs=(out_cap,)), total


_slice_range = jax.jit(_slice_range_impl, static_argnames=("out_cap",))


def _slice_range_factory(out_cap: int):
    return lambda level, a, b: _slice_range_impl(level, a, b, out_cap)


def _filter_window_impl(batch: Batch, a, b) -> Batch:
    k0 = batch.keys[0]
    keep = (batch.weights != 0) & (k0 >= jnp.asarray(a, k0.dtype)) & \
        (k0 < jnp.asarray(b, k0.dtype))
    return batch.compacted(keep)


_filter_window = jax.jit(_filter_window_impl)


def _filter_window_factory():
    return _filter_window_impl


class RangeExtract:
    """Grow-on-demand host driver for [a, b) slices across spine levels.
    Sharded levels slice per worker (the bounds are global scalars); the
    capacity check takes the worst worker."""

    def __init__(self):
        self.caps: Dict[int, int] = {}

    @staticmethod
    def _launch(level, a, b, cap):
        if level.sharded:
            from dbsp_tpu.parallel.lift import lifted

            # scalars ride the worker axis as [W] broadcasts (spmd shards
            # every argument; the per-worker body squeezes them back)
            w = level.weights.shape[0]
            return lifted(_slice_range_factory, cap)(
                level, jnp.full((w,), a, jnp.int64),
                jnp.full((w,), b, jnp.int64))
        return _slice_range(level, a, b, cap)

    def __call__(self, levels, a, b) -> List[Batch]:
        import numpy as np

        outs = []
        for level in levels:
            cap = self.caps.get(level.cap, 64)
            out, total = self._launch(level, a, b, cap)
            t = int(np.max(jax.device_get(total)))
            if t > cap:
                cap = bucket_cap(t)
                self.caps[level.cap] = cap
                out, _ = self._launch(level, a, b, cap)
            outs.append(out)
        return outs


class WindowOp(BinaryOperator):
    name = "window"

    def __init__(self, schema, gc: bool = False):
        self.schema = schema
        self.gc = gc
        self.prev: Optional[Tuple[int, int]] = None
        self._extract = RangeExtract()

    def clock_start(self, scope: int) -> None:
        self.prev = None

    def eval(self, view: TraceView, bounds) -> Batch:
        if bounds is None:
            return Batch.empty(*self.schema,
                               lead=tuple(view.delta.weights.shape[:-1]))
        a1, b1 = bounds
        a0, b0 = self.prev if self.prev is not None else (a1, a1)
        assert a1 >= a0 and b1 >= b0, (
            f"window bounds must be monotone: {(a0, b0)} -> {(a1, b1)}")
        self.prev = (a1, b1)

        if view.delta.sharded:
            from dbsp_tpu.parallel.lift import lifted

            w = view.delta.weights.shape[0]
            parts = [lifted(_filter_window_factory)(
                view.delta, jnp.full((w,), a1, jnp.int64),
                jnp.full((w,), b1, jnp.int64))]
        else:
            parts = [_filter_window(view.delta, a1, b1)]
        parts += [b.neg() for b in
                  self._extract(view.pre_levels, a0, min(a1, b0))]
        parts += self._extract(view.pre_levels, max(b0, a1), b1)
        out = parts[0] if len(parts) == 1 else \
            concat_batches(parts).consolidate().shrink_to_fit()
        if self.gc:
            view.spine.truncate_keys_below((a1,))
        return out


    def state_dict(self):
        return {"prev": self.prev}

    def load_state_dict(self, state):
        self.prev = tuple(state["prev"]) if state["prev"] is not None else None


@stream_method
def window(self: Stream, bounds: Stream, gc: bool = False) -> Stream:
    """Windowed view of this stream: rows whose first key column is inside
    the (monotone) bounds emitted by ``bounds`` this tick.

    ``gc=True`` reclaims trace state below the lower bound; enable only when
    this window is the sole consumer of the stream's trace (shared traces use
    the tightest common bound — reference TraceBounds semantics — which the
    host driver must coordinate).

    Sharded streams stay sharded (the reference's window self-shards its
    trace the same way, time_series/mod.rs): bounds are global scalars, each
    worker slices its own key range, and the union of per-worker slices IS
    the window of the union."""
    from dbsp_tpu.operators.registry import require_schema

    schema = require_schema(self, "window")
    t = self.trace()
    out = self.circuit.add_binary_operator(WindowOp(schema, gc), t, bounds)
    out.schema = schema
    out.key_sharded = getattr(t, "key_sharded", False)
    return out
