"""Hierarchical time-aggregate index — the radix tree.

Reference: ``crates/dbsp/src/operator/time_series/radix_tree/mod.rs:1-55``
(+ ``updater.rs``, ``tree_aggregate.rs``, ``partitioned_tree_aggregate.rs``):
the reference maintains, per partition key, aggregates over aligned time
buckets at geometric granularities, so that ANY time range decomposes into
O(log(range)) precomputed buckets and stays cheap to maintain under
out-of-order inserts and retractions.

TPU-native shape: tree level ``L`` (1-based) is a host-side
:class:`~dbsp_tpu.trace.Spine` keyed ``(partition, prefix)`` whose value
column is the aggregate over the aligned bucket
``[prefix * R^L, (prefix+1) * R^L)``, ``R = 1 << radix_bits``. Level 0 is
the raw ``(partition, time)`` input trace itself — never duplicated. The
level count is fixed at construction from ``max_time_range`` (the largest
range queries will ask for), so update and query loops are static — no
data-dependent host control flow.

Maintenance is bottom-up and delta-proportional (updater.rs semantics):
the tick's delta dirties level-1 prefixes; each dirty bucket recomputes by
a range-gather + segment-reduce from the level below and diffs against the
stored spine (retract old row / insert new); dirty prefixes shift right by
``radix_bits`` to seed the next level. Late/out-of-order inserts and
retractions need no special casing — whatever buckets the delta touches
are recomputed from the ground truth below. Per tick the work is
O(levels * |touched prefixes| * R), independent of total history.

Queries (tree_aggregate semantics): ``query(qp, qlo, qhi, ...)`` returns,
per query row, the aggregate over partition ``qp``'s rows with time in
``[qlo, qhi]``. Working in level-L position space (one position = R^L time
ticks): positions whose parent bucket lies fully inside the range are
covered by the next level; this level gathers only the left/right fringe
positions (< R each side). Gathered rows per query are therefore
O(R * levels) instead of O(range) — the whole point of the index.

Aggregator contract: ``leaf_agg`` turns raw rows into a bucket value;
``combine_agg`` combines bucket VALUES (weight-1 rows) into coarser buckets
and query answers, and must satisfy
``combine(leaf(A), leaf(B)) == leaf(A ∪ B)``. Max/Min/Sum combine with
themselves; Count combines with Sum (bucket counts add — re-counting bucket
rows would be wrong). Average is not a semigroup (avg of avgs) — linear
aggregates should index (sum, count) as two trees or a Fold.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dbsp_tpu.operators.aggregate import Aggregator, _reduce_groups
from dbsp_tpu.parallel.lift import lifted
from dbsp_tpu.trace.spine import Spine
from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch, bucket_cap

# Sharded execution: under a multi-worker mesh the rolling operator routes
# rows by the partition column's hash, so every partition's history lives
# wholly on one worker and the tree decomposes into W independent
# per-worker trees over [W, cap] level batches. Each jitted kernel below
# keeps its 1-D body and dispatches through ``lifted`` when its operands
# carry a worker axis; grow-on-demand capacity checks take the worst
# worker. Maintenance and queries therefore never leave the mesh — the
# host drives the same loop, over per-worker slices.


# ---------------------------------------------------------------------------
# Range gather over (partition, position)-keyed spines
# ---------------------------------------------------------------------------


def _range_gather_impl(qp, qlo, qhi, qlive, level: Batch, out_cap: int):
    """Rows of a (p, pos)-keyed level with p == qp[i] and pos in [qlo, qhi];
    returns (qrow, pos col, value col, weights, total), sorted by
    (qrow, pos). Dead slots carry qrow == len(qp) (the trash segment).
    Empty ranges (qhi < qlo) gather nothing. One-level instance of the
    aggregate family's shared cursor entry point (cursor.gather_ladder
    with distinct lo/hi probe columns + the pos key column gathered
    back); the per-level loop stays here because the tree's consumers
    want per-level parts with per-level caps."""
    from dbsp_tpu.zset import cursor

    tk = level.keys[1]
    (qrow, cols, w), total = cursor.gather_ladder(
        (qp, qlo.astype(tk.dtype)), qlive, (level,), out_cap,
        qhi_keys=(qp, qhi.astype(tk.dtype)), gather_keys=1)
    return qrow, cols[0], cols[1], w, total


_range_gather = jax.jit(_range_gather_impl, static_argnames=("out_cap",))


def _range_gather_factory(out_cap: int):
    return lambda qp, qlo, qhi, qlive, level: _range_gather_impl(
        qp, qlo, qhi, qlive, level, out_cap)


class RangeGather:
    """Grow-on-demand driver for vectorized [lo, hi] range gathers over a
    spine's batches; one batched overflow sync per call. Counts gathered
    slot capacity (tests assert the O(log) query-cost scaling). Sharded
    levels gather per worker; capacity checks take the worst worker."""

    def __init__(self):
        self.caps: Dict[int, int] = {}
        self.rows_gathered = 0

    @staticmethod
    def _launch(qp, qlo, qhi, qlive, level: Batch, cap: int):
        if level.sharded:
            return lifted(_range_gather_factory, cap)(qp, qlo, qhi, qlive,
                                                      level)
        return _range_gather(qp, qlo, qhi, qlive, level, cap)

    def __call__(self, qp, qlo, qhi, qlive, levels: Sequence[Batch],
                 q_cap: int):
        parts, totals, caps = [], [], []
        for level in levels:
            cap = self.caps.get(level.cap, max(64, q_cap))
            out = self._launch(qp, qlo, qhi, qlive, level, cap)
            parts.append(out[:4])
            totals.append(out[4])
            caps.append(cap)
        if not parts:
            return None
        tvals = jax.device_get(totals)
        for i, t in enumerate(tvals):
            t = int(np.max(t))
            if t > caps[i]:
                cap = bucket_cap(t)
                self.caps[levels[i].cap] = cap
                out = self._launch(qp, qlo, qhi, qlive, levels[i], cap)
                parts[i] = out[:4]
        self.rows_gathered += int(sum(np.max(t) for t in tvals))
        return [(qrow, (t, v), w) for qrow, t, v, w in parts]


# ---------------------------------------------------------------------------
# The tree
# ---------------------------------------------------------------------------


def _depth_for(max_time_range: int, radix_bits: int) -> int:
    """Levels so the top bucket is at least the largest query range."""
    levels = 1
    while (1 << (radix_bits * levels)) <= max_time_range:
        levels += 1
    return levels


def combine_for(agg: Aggregator) -> Aggregator:
    """Default combine semigroup for a built-in leaf aggregator."""
    from dbsp_tpu.operators.aggregate import Count, Max, Min, Sum

    if isinstance(agg, Count):
        return Sum(0)
    if isinstance(agg, (Max, Min, Sum)):
        return type(agg)(0)
    raise TypeError(
        f"no default combine semigroup for {agg.name}; pass combine_agg=")


class RadixTimeIndex:
    """Per-partition hierarchical time aggregates (see module doc)."""

    def __init__(self, leaf_agg: Aggregator, part_dtype, time_dtype,
                 max_time_range: int, radix_bits: int = 4,
                 combine_agg: Optional[Aggregator] = None):
        assert len(leaf_agg.out_dtypes) == 1, (
            "RadixTimeIndex needs a single-column aggregator")
        self.agg = leaf_agg
        self.combine = combine_agg if combine_agg is not None \
            else combine_for(leaf_agg)
        self.radix_bits = radix_bits
        self.nlevels = _depth_for(max_time_range, radix_bits)
        self.part_dtype = jnp.dtype(part_dtype)
        self.time_dtype = jnp.dtype(time_dtype)
        # level L (1-based): (p, prefix) -> bucket aggregate
        self.levels: List[Spine] = [
            Spine((self.part_dtype, self.time_dtype),
                  tuple(leaf_agg.out_dtypes))
            for _ in range(self.nlevels)]
        self._child_gather = [RangeGather() for _ in range(self.nlevels)]
        self._old_gather = [RangeGather() for _ in range(self.nlevels)]
        self._query_gather = [RangeGather() for _ in range(self.nlevels + 1)]

    @property
    def query_rows_gathered(self) -> int:
        return sum(g.rows_gathered for g in self._query_gather)

    # -- maintenance --------------------------------------------------------
    def update(self, delta: Batch, trace_levels: Sequence[Batch]) -> None:
        """Fold the tick's (p, t)-keyed delta into the tree.

        ``trace_levels``: the POST-tick spine levels of the raw input trace
        (level 0 — the recompute source of truth for level 1).
        """
        if int(delta.live_count()) == 0:
            return
        bits = self.radix_bits
        dp = delta.keys[0]
        dt = delta.keys[1]
        live = delta.weights != 0
        p, pref = _unique_prefixes(dp, (dt >> bits).astype(dt.dtype), live)
        p, pref = _trim(p, pref)
        for L in range(1, self.nlevels + 1):
            child = trace_levels if L == 1 else self.levels[L - 2].batches
            self._update_level(L, p, pref, child)
            if L < self.nlevels:
                p, pref = _unique_prefixes(
                    p, (pref >> bits).astype(pref.dtype),
                    p != kernels.sentinel_for(p.dtype))
                p, pref = _trim(p, pref)

    def _update_level(self, L: int, p, pref, child_levels) -> None:
        """Recompute the (p, pref) buckets of level L from the level below.

        In the child's position space one bucket spans R positions
        (for L == 1 the children are raw rows, whose positions are times).
        """
        bits = self.radix_bits
        spine = self.levels[L - 1]
        q_cap = p.shape[-1]  # last axis: [q] or sharded [W, q]
        qlive = p != kernels.sentinel_for(p.dtype)
        clo = pref << bits
        chi = ((pref + 1) << bits) - 1
        gathered = self._child_gather[L - 1](p, clo, chi, qlive,
                                             child_levels, q_cap)
        if gathered is None:
            new_vals = (jnp.zeros(p.shape, self.agg.out_dtypes[0]),)
            new_present = jnp.zeros(p.shape, jnp.bool_)
        else:
            # reduce on the value column; the position column rides along
            # in the parts only to keep rows distinct while netting.
            # Level 1 aggregates raw rows (leaf), higher levels combine
            # bucket values.
            red = self.agg if L == 1 else self.combine
            parts = tuple((qrow, (t, v), w) for qrow, (t, v), w in gathered)
            new_vals, new_present = _reduce_groups(parts, _OnCol1(red),
                                                   q_cap)
        old = self._old_gather[L - 1](p, pref, pref, qlive, spine.batches,
                                      q_cap)
        if old is None:
            old_vals = (kernels.sentinel_fill(p.shape,
                                              self.agg.out_dtypes[0]),)
            old_present = jnp.zeros(p.shape, jnp.bool_)
        else:
            parts = tuple((qrow, (t, v), w) for qrow, (t, v), w in old)
            old_vals, old_present = _reduce_groups(parts, _KeepCol1(), q_cap)
        diff = _bucket_diff(p, pref, qlive, new_vals[0], new_present,
                            old_vals[0], old_present)
        spine.insert(diff.shrink_to_fit())

    # -- queries -------------------------------------------------------------
    def query(self, qp, qlo, qhi, qlive, trace_levels: Sequence[Batch],
              q_cap: int):
        """Aggregate over raw-time range [qlo, qhi] per query row.

        Returns (vals tuple, present mask) aligned with the queries;
        ``present`` means at least one raw row lies in the range.
        """
        bits = self.radix_bits
        B = 1 << bits
        raw_parts: list = []     # level-0 rows -> leaf aggregation
        bucket_parts: list = []  # level>=1 bucket values -> combine

        def add(sink, gathered):
            if gathered:
                sink.extend((qrow, (t, v), w) for qrow, (t, v), w in gathered)

        lo = jnp.asarray(qlo, jnp.int64)
        hi = jnp.asarray(qhi, jnp.int64)
        active = qlive & (lo <= hi)
        for L in range(0, self.nlevels + 1):
            levels = trace_levels if L == 0 else self.levels[L - 1].batches
            sink = raw_parts if L == 0 else bucket_parts
            last = L == self.nlevels
            nlo = (lo + B - 1) // B   # first next-level position fully inside
            nhi = (hi + 1) // B       # exclusive end of covered positions
            covered = (nlo < nhi) & (not last)
            left_hi = jnp.where(covered, nlo * B - 1, hi)
            right_lo = jnp.where(covered, nhi * B, hi + 1)
            add(sink, self._query_gather[L](qp, lo, left_hi, active, levels,
                                            q_cap))
            add(sink, self._query_gather[L](qp, right_lo, hi,
                                            active & covered, levels, q_cap))
            lo, hi, active = nlo, nhi - 1, active & covered

        def reduce(parts, agg):
            if not parts:
                return (jnp.zeros(qp.shape, self.agg.out_dtypes[0]),
                        jnp.zeros(qp.shape, jnp.bool_))
            vals, present = _reduce_groups(tuple(parts), _OnCol1(agg), q_cap)
            return vals[0], present

        raw_val, raw_present = reduce(raw_parts, self.agg)
        buck_val, buck_present = reduce(bucket_parts, self.combine)
        val, present = _combine_partials(
            raw_val, raw_present, buck_val, buck_present, self.combine,
            q_cap)
        return (val,), present

    # -- views ---------------------------------------------------------------
    def to_dicts(self):
        return [lvl.to_dict() for lvl in self.levels]

    def state_dict(self):
        return {"levels": self.levels}

    def load_state_dict(self, state):
        self.levels = state["levels"]


# ---------------------------------------------------------------------------
# Helper aggregators over (position, value) part columns
# ---------------------------------------------------------------------------


class _OnCol1(Aggregator):
    """Apply the user aggregator to value column 1 of (pos, value) parts."""

    def __init__(self, agg: Aggregator):
        self.agg = agg
        self.out_dtypes = agg.out_dtypes
        self.name = f"oncol1<{agg.name}>"

    def __hash__(self):  # jit static identity
        return hash(("oncol1", self.agg))

    def __eq__(self, other):
        return isinstance(other, _OnCol1) and self.agg == other.agg

    def reduce(self, val_cols, weights, seg, num_segments):
        return self.agg.reduce(val_cols[1:], weights, seg, num_segments)


class _KeepCol1(Aggregator):
    """Recover the unique stored row's value per bucket (col 1 of parts)."""

    out_dtypes = (jnp.int64,)
    name = "keep1"

    def __hash__(self):
        return hash("keep1")

    def __eq__(self, other):
        return isinstance(other, _KeepCol1)

    def reduce(self, val_cols, weights, seg, num_segments):
        v = val_cols[1]
        lo = (jnp.iinfo(v.dtype).min
              if jnp.issubdtype(v.dtype, jnp.integer) else -jnp.inf)
        return (jax.ops.segment_max(jnp.where(weights > 0, v, lo), seg,
                                    num_segments=num_segments),)


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


def _combine_partials_impl(raw_val, raw_present, buck_val, buck_present,
                           combine: Aggregator, q_cap: int):
    """Fold the raw-fringe partial and the bucket partial per query row with
    the combine semigroup (absent partials are masked by weight 0)."""
    seg = jnp.concatenate([jnp.arange(q_cap, dtype=jnp.int32)] * 2)
    vals = jnp.concatenate([raw_val, buck_val])
    w = jnp.concatenate([jnp.where(raw_present, 1, 0),
                         jnp.where(buck_present, 1, 0)]).astype(jnp.int64)
    out = combine.reduce((vals,), w, seg, q_cap)
    return out[0], raw_present | buck_present


_combine_partials_jit = jax.jit(_combine_partials_impl,
                                static_argnames=("combine", "q_cap"))


def _combine_partials_factory(combine: Aggregator, q_cap: int):
    return lambda rv, rp, bv, bp: _combine_partials_impl(rv, rp, bv, bp,
                                                         combine, q_cap)


def _combine_partials(raw_val, raw_present, buck_val, buck_present,
                      combine: Aggregator, q_cap: int):
    if raw_present.ndim > 1:  # sharded query rows
        return lifted(_combine_partials_factory, combine, q_cap)(
            raw_val, raw_present, buck_val, buck_present)
    return _combine_partials_jit(raw_val, raw_present, buck_val,
                                 buck_present, combine, q_cap)


def _unique_prefixes_impl(p, pref, live):
    """Distinct live (p, prefix) pairs, compacted to the front. Inputs are
    sorted by (p, t) and prefixing is monotone in t, so (p, pref) stays
    sorted and distinctness is an adjacent-equality check."""
    p = jnp.where(live, p, kernels.sentinel_for(p.dtype))
    pref = jnp.where(live, pref, kernels.sentinel_for(pref.dtype))
    dup = kernels.rows_equal_prev((p, pref), n=p.shape[0])
    keep = ~dup & live
    cols, _ = kernels.compact((p, pref),
                              jnp.where(keep, 1, 0).astype(jnp.int32), keep)
    return cols[0], cols[1]


_unique_prefixes_jit = jax.jit(_unique_prefixes_impl)


def _unique_prefixes_factory():
    return _unique_prefixes_impl


def _unique_prefixes(p, pref, live):
    if live.ndim > 1:
        return lifted(_unique_prefixes_factory)(p, pref, live)
    return _unique_prefixes_jit(p, pref, live)


def _trim(p, pref):
    """Re-bucket compacted (p, pref) columns to the live count (one sync) —
    keeps every per-level kernel sized by touched prefixes. Sharded
    columns bucket by the worst worker (every slice shares one cap)."""
    live = p != kernels.sentinel_for(p.dtype)
    n = int(jnp.max(jnp.sum(live, axis=-1))) if p.ndim > 1 \
        else int(jnp.sum(live))
    cap = bucket_cap(max(n, 1))
    if cap < p.shape[-1]:
        p, pref = p[..., :cap], pref[..., :cap]
    return p, pref


def _bucket_diff_impl(p, pref, qlive, new_vals, new_present, old_vals,
                      old_present):
    """Retract/insert delta batch for the (p, prefix) bucket rows."""
    changed = (new_present != old_present) | \
        ~kernels._col_eq(new_vals.astype(old_vals.dtype), old_vals)
    ins = jnp.where(qlive & new_present & changed, 1, 0)
    ret = jnp.where(qlive & old_present & changed, -1, 0)
    keys = (jnp.concatenate([p, p]), jnp.concatenate([pref, pref]))
    vals = (jnp.concatenate([new_vals.astype(old_vals.dtype), old_vals]),)
    w = jnp.concatenate([ins, ret]).astype(jnp.int64)
    cols, w = kernels.consolidate_cols((*keys, *vals), w)
    return Batch(cols[:2], cols[2:], w)


_bucket_diff_jit = jax.jit(_bucket_diff_impl)


def _bucket_diff_factory():
    return _bucket_diff_impl


def _bucket_diff(p, pref, qlive, new_vals, new_present, old_vals,
                 old_present):
    if qlive.ndim > 1:
        return lifted(_bucket_diff_factory)(p, pref, qlive, new_vals,
                                            new_present, old_vals,
                                            old_present)
    return _bucket_diff_jit(p, pref, qlive, new_vals, new_present,
                            old_vals, old_present)
