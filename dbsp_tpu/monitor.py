"""TraceMonitor: validates the engine's event streams and renders circuits.

Reference: ``monitor/mod.rs:131`` — a state machine over CircuitEvents and
SchedulerEvents that panics on protocol violations (eval outside a step,
unbalanced start/end, events for unknown nodes), used as a test oracle inside
engine tests; plus ``visualize_circuit`` (:167) rendering the circuit graph
to graphviz.

Relationship to ``dbsp_tpu.obs``: three consumers share the event streams
with distinct jobs — the monitor is the *correctness oracle* (validates
protocol, stores no timings), ``obs.CircuitInstrumentation`` is the
production *measurement* consumer (histograms, gauges, Chrome-trace
spans), and ``obs.flight.HostFlightSource`` + ``obs.slo.SLOWatchdog`` are
the *incident-capture* layer (per-tick events with attributed causes in a
bounded ring; SLO breaches freeze windows into ``/incidents`` reports).
All attach via the same ``register_*_event_handler`` API and compose
freely.

Durability events live in the same incident-capture layer: periodic
checkpoints and deploy-time restores (``dbsp_tpu.checkpoint``) record
``checkpoint``/``restore`` flight events, and a corrupted-generation
fallback or failed restore surfaces as a ``restore`` incident at
``/incidents`` (README §Durability) — the oracle here never sees them
because they are control-plane actions, not scheduler protocol.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from dbsp_tpu.circuit.builder import Circuit, CircuitEvent, SchedulerEvent


class TraceMonitorError(AssertionError):
    pass


class TraceMonitor:
    """Attach before building operators to observe construction too."""

    def __init__(self, circuit: Circuit, panic: bool = True):
        self.panic = panic
        self.errors: List[str] = []
        self.known_nodes: Set[tuple] = set()
        self.edges: List[tuple] = []
        self.names: Dict[tuple, str] = {}
        self._step_depth = 0  # nested circuits interleave their own steps
        self._evaluating: Set[tuple] = set()
        self._clock_running = False
        circuit.register_circuit_event_handler(self._on_circuit_event)
        circuit.register_scheduler_event_handler(self._on_scheduler_event)

    def _fail(self, msg: str) -> None:
        self.errors.append(msg)
        if self.panic:
            raise TraceMonitorError(msg)

    # -- construction events ------------------------------------------------
    def _on_circuit_event(self, ev: CircuitEvent) -> None:
        if ev.kind in ("operator", "subcircuit"):
            if ev.node_id in self.known_nodes:
                self._fail(f"duplicate node id {ev.node_id}")
            self.known_nodes.add(ev.node_id)
            self.names[ev.node_id] = ev.name or ev.kind
        elif ev.kind == "edge":
            if ev.from_id not in self.known_nodes:
                self._fail(f"edge from unknown node {ev.from_id}")
            self.edges.append((ev.from_id, ev.to_id))

    # -- runtime events -----------------------------------------------------
    def _on_scheduler_event(self, ev: SchedulerEvent) -> None:
        if ev.kind == "clock_start":
            if self._clock_running:
                self._fail("clock started twice")
            self._clock_running = True
        elif ev.kind == "clock_end":
            if not self._clock_running:
                self._fail("clock_end without clock_start")
            self._clock_running = False
        elif ev.kind == "step_start":
            self._step_depth += 1
        elif ev.kind == "step_end":
            if self._step_depth == 0:
                self._fail("step_end without step_start")
            else:
                self._step_depth -= 1
            if self._step_depth == 0 and self._evaluating:
                self._fail(f"step ended while evaluating {self._evaluating}")
        elif ev.kind == "eval_start":
            if self._step_depth == 0:
                self._fail(f"eval of {ev.node_id} outside a step")
            if ev.node_id in self._evaluating:
                self._fail(f"re-entrant eval of {ev.node_id}")
            if ev.node_id not in self.known_nodes:
                self._fail(f"eval of unknown node {ev.node_id}")
            self._evaluating.add(ev.node_id)
        elif ev.kind == "eval_end":
            if ev.node_id not in self._evaluating:
                self._fail(f"eval_end without eval_start for {ev.node_id}")
            self._evaluating.discard(ev.node_id)

    # -- visualization (reference: visualize_circuit, monitor/mod.rs:167) ---
    def visualize(self) -> str:
        lines = ["digraph circuit {", '  rankdir="LR";']
        for gid in sorted(self.known_nodes):
            name = "n" + "_".join(map(str, gid))
            lines.append(f'  {name} [label="{self.names[gid]}"];')
        for frm, to in self.edges:
            a = "n" + "_".join(map(str, frm))
            b = "n" + "_".join(map(str, to))
            lines.append(f"  {a} -> {b};")
        lines.append("}")
        return "\n".join(lines)
