"""Compile a built circuit into ONE jitted step function.

Why this exists (the TPU-first argument): the host-driven scheduler evaluates
operators one kernel launch at a time and makes host-side decisions (grow-on-
demand capacities, spine merge scheduling, overflow checks) that each cost a
device->host round-trip. On a directly-attached accelerator those are ~us;
over a tunneled TPU they measure ~90ms EACH, and even locally they forbid XLA
from fusing across operator boundaries. Compiled mode removes the host from
the per-tick path entirely:

  * the scheduler's toposort eval sequence is traced ONCE into a single
    ``step(states, tick, feeds) -> (states', outputs, required)`` function —
    XLA sees the whole tick and fuses/overlaps across operators;
  * every state (traces, accumulators) is a fixed-capacity device batch
    threaded through the function — no Python bookkeeping per tick;
  * all data-dependent capacity decisions become device-side "required
    capacity" scalars, reduced to a running max; the runner checks them at
    validation points (every N ticks / end of run), and on overflow grows the
    capacity, re-traces, and REPLAYS from the last validated snapshot —
    deterministic inputs (tick-indexed generators, retained feeds) make the
    replay exact. Optimistic execution + epoch validation, in place of the
    host path's per-eval synchronous checks.

The input side can be closed over too: pass ``gen_fn(tick) -> feeds`` (e.g.
:func:`dbsp_tpu.nexmark.device_gen.generate_tick`) and event generation joins
the same XLA program — a benchmark tick then transfers NOTHING between host
and device.

Between-tick discipline (the wall-clock side of the contract): ticks run
PIPELINED at depth 1 (``_run_pipelined`` — dispatch t, wait t-1), snapshots
are INCREMENTAL (deep trace levels are version-counted and only re-copied
after a drain touched them), and LSM maintenance is BUDGETED
(``DBSP_TPU_MAINTAIN_BUDGET_ROWS`` bounds rows moved per ``maintain`` call,
with a resumable prefix-slice cursor), so no single tick absorbs a drain
cascade and host work per interval is O(level 0 + budget), not O(state).
Each between-tick phase is timed into ``host_overhead_ns`` and annotated
onto the next latency sample (``tick_causes``) — tail ticks are attributable
to maintain / snapshot / retrace from the bench output alone.
``tools/check_hotpath.py`` (rule 3) keeps new syncs out of the step loop.

Reference analog: ``crates/dataflow-jit`` (compile the dataflow once,
schema-driven, no per-record interpretation) — here XLA is the codegen and
the circuit graph is the IR (SURVEY.md §2.4).

Supported operators: input/output handles, map/filter/flat_map/index, plus/
minus/neg/sum, trace, join, aggregate (general + linear), distinct,
watermark/apply/window (scalar streams become (valid, value) device pairs;
window GC feeds back into the trace state inside the program). Circuits
using other operators (nested/recursive children, async transports) stay on
the host-driven path — the two modes share kernels and state layouts, so
they compose (warm up host-side, then compile; or run host-side features
around a compiled core).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dbsp_tpu import residency as res
from dbsp_tpu.circuit.scheduler import static_schedule
from dbsp_tpu.compiled import cnodes
from dbsp_tpu.compiled.cnodes import CNode
from dbsp_tpu.zset.batch import Batch, bucket_cap

# Maintenance budget (rows MOVED between trace levels per maintain() call).
# Bounding the per-call drain volume amortizes an LSM cascade over several
# validation intervals instead of letting one tick absorb l0->l1->...->tail
# in a single burst (the 8.3x p99/p50 tail measured in BENCH r05). The knob
# (DBSP_TPU_MAINTAIN_BUDGET_ROWS; <=0 = unbounded) is OWNED by the host
# spine and imported here so both engines share one amortization discipline.
from dbsp_tpu.trace.spine import MAINTAIN_BUDGET_ROWS  # noqa: E402


class CompiledOverflow(RuntimeError):
    """A static capacity was exceeded since the last validation point.

    ``items`` is a list of (cnode, cap_key, required) — the runner's
    ``grow()`` consumes it; state since the last snapshot is invalid and must
    be replayed after growing.
    """

    def __init__(self, items):
        self.items = items
        msg = ", ".join(f"{c.op.name}.{k}: need {r} > cap {c.caps[k]}"
                        for c, k, r in items)
        super().__init__(f"compiled capacities exceeded: {msg}")


class _Ctx:
    """Per-trace context: feeds in, outputs + capacity requirements out."""

    def __init__(self, feeds):
        self.feeds = feeds
        self.outputs: Dict[int, Batch] = {}
        self.reqs: List[jnp.ndarray] = []
        self.req_index: List[Tuple[CNode, str]] = []
        # trace-node index -> lower bound: window GC feeding back into the
        # trace state within the same program (TraceBound semantics)
        self.gc_bounds: Dict[int, jnp.ndarray] = {}

    def require(self, cnode: CNode, key: str, scalar) -> None:
        self.req_index.append((cnode, key))
        self.reqs.append(jnp.asarray(scalar, jnp.int64))


def _cnode_for(node) -> CNode:
    from dbsp_tpu.operators.aggregate import AggregateOp
    from dbsp_tpu.operators.aggregate_linear import LinearAggregateOp
    from dbsp_tpu.operators.basic import Minus, Neg, Plus, SumN
    from dbsp_tpu.operators.distinct import DistinctOp, StreamDistinct
    from dbsp_tpu.operators.filter_map import FilterOp, FlatMapOp, MapOp
    from dbsp_tpu.operators.io_handles import OutputOperator, ZSetInput
    from dbsp_tpu.operators.join import JoinOp
    from dbsp_tpu.operators.trace_op import TraceOp

    op = node.operator
    if isinstance(op, ZSetInput):
        return cnodes.CInput(node, op)
    if isinstance(op, (MapOp, FilterOp, FlatMapOp)):
        return cnodes.CPure(node, op)
    if isinstance(op, StreamDistinct):
        return cnodes.CStreamDistinct(node, op)
    if isinstance(op, TraceOp):
        return cnodes.CTrace(node, op)
    if isinstance(op, JoinOp):
        return cnodes.CJoin(node, op)
    if isinstance(op, AggregateOp):
        return cnodes.CAggregate(node, op)
    if isinstance(op, LinearAggregateOp):
        return cnodes.CLinearAggregate(node, op)
    if isinstance(op, DistinctOp):
        return cnodes.CDistinct(node, op)
    if isinstance(op, Plus):
        return cnodes.CPlus(node, op)
    if isinstance(op, Neg):
        return cnodes.CNeg(node, op)
    if isinstance(op, SumN):
        return cnodes.CSumN(node, op)
    if isinstance(op, OutputOperator):
        return cnodes.COutput(node, op)
    if isinstance(op, Minus):
        return cnodes.CMinus(node, op)
    from dbsp_tpu.operators.basic import Apply
    from dbsp_tpu.operators.shard_op import ExchangeOp, UnshardOp
    from dbsp_tpu.timeseries.watermark import WatermarkMonotonic
    from dbsp_tpu.timeseries.window import WindowOp

    if isinstance(op, ExchangeOp):
        return cnodes.CExchange(node, op)
    if isinstance(op, UnshardOp):
        return cnodes.CUnshard(node, op)
    from dbsp_tpu.operators.topk import TopKOp

    if isinstance(op, TopKOp):
        return cnodes.CTopK(node, op)
    if isinstance(op, WatermarkMonotonic):
        return cnodes.CWatermark(node, op)
    if isinstance(op, Apply):
        return cnodes.CApply(node, op)
    if isinstance(op, WindowOp):
        return cnodes.CWindow(node, op)
    from dbsp_tpu.operators.join_range import RangeJoinOp
    from dbsp_tpu.operators.upsert import UpsertInput
    from dbsp_tpu.timeseries.rolling import RollingAggregateOp

    if isinstance(op, RangeJoinOp):
        return cnodes.CRangeJoin(node, op)
    if isinstance(op, RollingAggregateOp):
        return cnodes.CRolling(node, op)
    if isinstance(op, UpsertInput):
        return cnodes.CUpsertIn(node, op)
    from dbsp_tpu.operators.z1 import Z1, _PlusNamed

    if isinstance(op, Z1):
        return cnodes.CZ1Output(node, op) if node.kind == "strict_output" \
            else cnodes.CZ1Input(node, op)
    if isinstance(op, _PlusNamed):
        return cnodes.CPlus(node, op)
    raise NotImplementedError(
        f"operator {op.name!r} ({type(op).__name__}) has no compiled "
        "equivalent yet — run this circuit on the host-driven path")


@jax.jit
def _copy_tree(tree):
    """Deep-copy a state pytree in ONE dispatch (eager per-leaf jnp.copy
    costs a dispatch per column, ~100 leaves per circuit)."""
    return jax.tree_util.tree_map(jnp.copy, tree)


@partial(jax.jit, static_argnums=(2,), donate_argnums=(0, 1))
def _drain_pair(receiver: Batch, source: Batch, cap: int):
    """One maintenance drain as a single jitted dispatch (eager Batch ops
    cost ~10 dispatches each; this runs every few validation intervals on
    every leveled trace, so dispatch overhead was measurable)."""
    return receiver.merge_with(source).with_cap(cap), source.masked(False)


@partial(jax.jit, static_argnums=(3,), donate_argnums=(0, 1))
def _drain_slice(receiver: Batch, source: Batch, n, cap: int):
    """Drain only the FIRST ``n`` live rows of ``source`` into ``receiver``
    — the resumable merge cursor of budgeted maintenance. Live rows are
    packed at the front of a consolidated level, so the taken prefix is
    itself a consolidated batch and the remainder keeps every level
    invariant; the cursor is implicitly always 0. A key split across the
    slice boundary lands in two levels, which consumers already net
    (``_reduce_groups_impl(..., net=True)``). The remainder re-packs by a
    ROLL (the kept rows are already contiguous at [n, live)), not a
    compaction — ``kernels.compact`` assumes an unsharded row axis, while
    levels here may carry a worker axis ([W, cap]); roll + mask work on
    the last axis of either layout. On a sharded level ``n`` applies
    per-worker slice (lives are max-worker counts, the same convention
    capacity bucketing uses)."""
    idx = jnp.arange(source.cap, dtype=jnp.int32)
    take = source.masked(idx < n)
    rolled = Batch(
        tuple(jnp.roll(k, -n, axis=-1) for k in source.keys),
        tuple(jnp.roll(v, -n, axis=-1) for v in source.vals),
        jnp.roll(source.weights, -n, axis=-1))
    # positions that wrapped around hold the taken prefix — dead them;
    # rolled live rows occupy [0, live - n), already packed at the front.
    # The remainder IS still one consolidated run (sorted suffix, packed,
    # sentinel tail) — tag it so the level's pytree aux stays IDENTICAL
    # across drains; an aux flip here would retrace the whole step program
    # on the next tick (run metadata is static data).
    rest = rolled.masked(idx < source.cap - n).tagged((source.cap,))
    return receiver.merge_with(take).with_cap(cap), rest


class CompiledHandle:
    """Drives a compiled circuit: step / validate / grow / snapshot-replay."""

    def __init__(self, circuit, gen_fn: Optional[Callable] = None,
                 runtime=None):
        self.circuit = circuit
        self.runtime = runtime  # needed for sharded host-side maintenance
        self.mesh = getattr(runtime, "mesh", None)
        self.workers = getattr(runtime, "workers", 1)
        self.order = static_schedule(circuit)
        self.cnodes: List[CNode] = [_cnode_for(n) for n in self.order]
        self.by_index = {cn.node.index: cn for cn in self.cnodes}
        # a GC'd trace is bounded by the window span, not the run length:
        # exclude it from linear presize projection (instance attr shadows
        # the class-level MONOTONE_CAPS)
        for cn in self.cnodes:
            # per-level consumers that were NOT fused over the expanded
            # slot ladder (range joins, windows, rolling aggregates) would
            # pay one launch per SLOT per tick — their input traces keep
            # the legacy merged l0 instead of slotting
            if isinstance(cn, (cnodes.CRangeJoin, cnodes.CWindow,
                               cnodes.CRolling)):
                for i in cn.node.inputs:
                    tgt = self.by_index.get(i)
                    if isinstance(tgt, cnodes.CTrace):
                        tgt._no_slots = True
            if isinstance(cn, cnodes.CWindow) and cn.op.gc:
                tgt = self.by_index.get(cn.node.inputs[0])
                if isinstance(tgt, cnodes.CTrace):
                    tgt.MONOTONE_CAPS = frozenset()
                    # in-program TraceBound truncation SHRINKS levels —
                    # maintain() must refetch exact live counts (its
                    # host cache only ever sees drains grow them) or the
                    # base_live requirement integrates upward forever
                    tgt._gc_refresh = True
        # map host InputHandle ops -> node indices (for feeds dicts)
        self._op_to_index = {id(n.operator): n.index for n in self.order}
        self._gen_fn = gen_fn
        self.deferred_consolidations = self._place_consolidations()
        self.states: Dict[str, Any] = {}
        for cn in self.cnodes:
            cn.lead = (self.workers,) if self.workers > 1 else ()
            st = cn.init_state()
            if st is not None:
                if self.workers > 1:
                    from dbsp_tpu.parallel.mesh import worker_sharding

                    st = jax.device_put(st, worker_sharding(self.mesh))
                self.states[str(cn.node.index)] = st
        self._step_jit = None
        # device-resident tick cursor: the step program RETURNS tick+1 (and
        # the scan program t0+n), so the steady state never uploads the
        # tick scalar — the old per-tick jnp.asarray(tick) was an implicit
        # h2d transfer on every dispatch, the exact class
        # jax.transfer_guard("disallow") convicts (testing/retrace.py).
        # _tick_host mirrors the device value; a mismatch (restore, replay,
        # manual tick jump) re-uploads EXPLICITLY via jax.device_put.
        self._tick_dev = None
        self._tick_host: Optional[int] = None
        # armed by testing/retrace.py's sentinel session: a
        # jax.transfer_guard level ("disallow") wrapped around the jitted
        # step/scan calls so implicit device<->host transfers in the
        # steady tick raise with a stack
        self._steady_guard: Optional[str] = None
        self._checks: List[Tuple[CNode, str]] = []
        self._req = None          # device running-max of requirements
        self._max_jit = jax.jit(jnp.maximum)
        self.last_outputs: Dict[int, Batch] = {}
        self.step_times_ns: List[int] = []
        # grow-and-replay cycles since construction (observability: the
        # obs registry exports this as
        # dbsp_tpu_compiled_overflow_replays_total)
        self.overflow_replays = 0
        # the subset caused by exchange/input bucket overflow (skew past a
        # static per-worker capacity) — exported as
        # dbsp_tpu_exchange_overflow_total and in bench detail
        self.exchange_overflows = 0
        # -- tail attribution + incremental-snapshot bookkeeping ------------
        # host_overhead_ns: wall time of each between-tick host phase (obs
        # exports dbsp_tpu_compiled_tick_host_overhead_seconds{phase});
        # tick_causes: (sample index, cause) annotations — a spike in
        # step_times_ns[i] is explained by the causes recorded against i
        # (bench.py turns these into per-cause spike counts)
        self.host_overhead_ns: Dict[str, List[int]] = {
            "validate": [], "maintain": [], "snapshot": []}
        self.tick_causes: List[Tuple[int, str]] = []
        self._pending_causes: set = set()
        # maintain amortization state (see maintain()): cumulative stats the
        # cascade test and obs read, plus the per-(state, level) version
        # counters the incremental snapshot uses to skip re-copying deep
        # levels that no drain has touched since the last snapshot
        self.maintain_stats: Dict[str, int] = {
            "calls": 0, "drains": 0, "partial_drains": 0, "rows_moved": 0,
            "max_slice_rows": 0, "max_budgeted_slice_rows": 0,
            "exempt_drains": 0}
        self.maintain_pending = False
        self._level_versions: Dict[str, List[int]] = {}
        self._snap_levels: Dict[str, List[Optional[Tuple[int, Batch]]]] = {}
        # hard-link scope marker for incremental checkpoints: assigned by
        # dbsp_tpu.checkpoint on first save, regenerated on restore (two
        # handles sharing a directory must never alias each other's blobs)
        self._ckpt_salt: Optional[str] = None
        # -- tiered trace residency (device <- host <- disk) -----------------
        # Residency bookkeeping lives OUTSIDE the jitted state pytree: the
        # step program is traced against a HOT pytree (donated, device) and
        # a COLD operand dict (numpy / memmap, device_put per call, buffers
        # die with it), so a demoted level never re-materializes as a
        # persistent program output. All transitions happen between
        # validated intervals (maintain / restore), never in the hot loop.
        self.residency_cfg: res.ResidencyConfig = res.ResidencyConfig.from_env()
        self._tiers: Dict[str, List[str]] = {}    # key -> tier per level
        self._cold_meta: Dict[str, Dict[int, dict]] = {}  # disk blob metas
        self._cold_store = None                   # residency.ColdStore
        self._lru: Dict[Tuple[str, int], int] = {}  # (key, lvl) -> interval
        self._interval = 0                        # maintain-call clock
        # transition observability: counts keyed (from, to, cause) +
        # bounded append-only log (CompiledFlightSource polls the tail into
        # `residency` flight events) + cold-blob corruption episodes
        # (polled into one-shot `restore` SLO incidents)
        self.residency_stats: Dict[Tuple[str, str, str], int] = {}
        self.residency_log: List[dict] = []
        self.cold_events: List[dict] = []

    # -- consolidate placement ----------------------------------------------
    def _place_consolidations(self) -> int:
        """Dedupe adjacent consolidations and defer them toward sinks.

        A consolidation is PURELY canonicalizing: it changes a batch's
        layout (sorted, netted, packed), never its Z-set value. When every
        consumer of a node re-canonicalizes anyway — a general map/flat_map
        (they consolidate after transforming, and row-wise transforms
        commute with netting), an n-ary sum (concat + consolidate), a
        key-hash exchange (consolidates after the all_to_all), or a host
        output sink (reads canonicalize lazily, see :meth:`output`) — the
        node's own trailing consolidation is dead work and is removed from
        the traced program (``defer_consolidate``). Order-preserving
        pass-throughs (filter, neg) inherit their consumers' requirement,
        so a join -> filter -> map chain defers the join's sort too.

        Everything stateful (traces, aggregates, distinct, plus/minus
        merges, windows, order-preserving maps) REQUIRES consolidated
        inputs and fences the deferral. Returns the number of deferred
        consolidations (each counted under ``path="deferred"`` in
        ``dbsp_tpu_zset_consolidate_total``).
        ``DBSP_TPU_DEFER_CONSOLIDATE=0`` disables the pass (bisect knob)."""
        import os

        from dbsp_tpu.operators.filter_map import FilterOp, FlatMapOp, MapOp
        from dbsp_tpu.zset import kernels as zkernels

        if os.environ.get("DBSP_TPU_DEFER_CONSOLIDATE", "1") == "0":
            return 0

        consumers: Dict[int, List[CNode]] = {}
        for cn in self.cnodes:
            for i in cn.node.inputs:
                consumers.setdefault(i, []).append(cn)

        def input_need(cn: CNode) -> bool:
            """Does ``cn`` require consolidated INPUT batches? (Consumers
            are resolved before producers — reversed toposort — so
            pass-through nodes may read their own ``_out_need``.)"""
            if isinstance(cn, cnodes.COutput):
                return False  # host reads canonicalize at the sink
            if isinstance(cn, cnodes.CExchange):
                return False  # consolidates after the all_to_all
            if isinstance(cn, cnodes.CSumN):
                # consolidates itself unless deferred — and deferral only
                # ever happens when its own consumers don't need
                # consolidated rows, so either way the inputs may arrive
                # unconsolidated
                return False
            if isinstance(cn, cnodes.CPure):
                op = cn.op
                if isinstance(op, FilterOp):
                    return getattr(cn, "_out_need", True)
                if isinstance(op, MapOp):
                    return op.preserves_order
                if isinstance(op, FlatMapOp):
                    return False
                return True
            if isinstance(cn, cnodes.CNeg):
                return getattr(cn, "_out_need", True)
            return True

        deferred = 0
        for cn in reversed(self.cnodes):
            cons = consumers.get(cn.node.index, [])
            cn._out_need = (not cons) or any(input_need(c) for c in cons)
            if cn._out_need:
                continue
            can_defer = isinstance(
                cn, (cnodes.CJoin, cnodes.CRangeJoin, cnodes.CSumN))
            if isinstance(cn, cnodes.CPure) and \
                    isinstance(cn.op, (MapOp, FlatMapOp)) and \
                    not getattr(cn.op, "preserves_order", False):
                can_defer = True
            if can_defer:
                cn.defer_consolidate = True
                deferred += 1
                zkernels.count_consolidate_path("deferred")
        return deferred

    # -- feeds ---------------------------------------------------------------
    def _feed_indices(self, feeds: Dict) -> Dict[int, Batch]:
        out = {}
        for h, b in feeds.items():
            op = getattr(h, "_op", h)  # InputHandle or raw operator
            out[self._op_to_index[id(op)]] = b
        return out

    # -- tiered trace residency ----------------------------------------------
    def set_residency(self, cfg: res.ResidencyConfig) -> None:
        """Apply one residency config (the pipeline-config / env merge) —
        the compiled half of the unified knob. Takes effect at the next
        maintain interval; sharded handles keep everything device-resident
        (cold operands cannot join the SPMD collectives, the same carve-out
        the host spine documents for sharded batches)."""
        if cfg == self.residency_cfg:
            return
        self.residency_cfg = cfg
        if self.mesh is not None:
            return
        if self._cold_store is not None and cfg.cold_dir and \
                self._cold_store.path != cfg.cold_dir:
            # the store is already materialized somewhere else (an env/
            # default temp dir from before this config arrived): keeping
            # it would silently strand all cold blobs outside the
            # configured directory — the accepted-but-ignored key again.
            # Fault the disk tier up (verified) so the old store owns
            # nothing, then let _store() lazily recreate at the new path;
            # enforcement re-demotes into it.
            for cn, key, (levels, base) in list(self._leveled_nodes()):
                tiers = list(self._tiers.get(key) or [])
                if res.TIER_DISK not in tiers:
                    continue
                levels = list(levels)
                for k, t in enumerate(tiers):
                    if t != res.TIER_DISK:
                        continue
                    ent = self._cold_meta.get(key, {}).get(k)
                    blob = ent["blob"] if ent is not None and \
                        ent.get("batch") is levels[k] \
                        else res.meta_from_batch(levels[k])
                    hot = res.fault_batch(blob, self._cold_store)
                    if ent is not None:
                        self._cold_meta[key].pop(k, None)
                        self._cold_store.release(ent["blob"])
                    levels[k] = hot
                    tiers[k] = res.TIER_HOST
                    self._log_transition(key, k, res.TIER_DISK,
                                         res.TIER_HOST, hot.cap, "config")
                self._tiers[key] = tiers
                cn.residency_tiers = tuple(tiers)
                self.states[key] = (tuple(levels), base)
            self._cold_store = None
        if cfg.active:
            # enforce immediately so a freshly deployed pipeline starts
            # within budget instead of waiting for the first drain
            self._enforce_residency(cause="config")
        elif self._tiers:
            # budgets DISABLED (explicit <= 0 config over an env knob):
            # promote everything back so the engine actually stops paying
            # the tiering, instead of stranding cold levels forever
            for cn, key, (levels, base) in list(self._leveled_nodes()):
                tiers = self._tiers.get(key)
                if not tiers:
                    continue
                levels = list(levels)
                for k, t in enumerate(tiers):
                    if t != res.TIER_DEVICE:
                        self._promote_level(cn, key, levels, tiers, k,
                                            "config")
                self._tiers.pop(key, None)
                cn.residency_tiers = tuple(tiers)
                self.states[key] = (tuple(levels), base)

    def _store(self) -> "res.ColdStore":
        if self._cold_store is None:
            path = self.residency_cfg.cold_dir
            if not path:
                # PER-HANDLE temp store, never the process-global default:
                # two handles sharing one store would cross-route their
                # corruption incidents (the observer is per store) and
                # cross-alias blob lifetimes
                import tempfile

                path = tempfile.mkdtemp(prefix="dbsp-tpu-cold-")
            self._cold_store = res.ColdStore(path,
                                             on_event=self._cold_event)
        return self._cold_store

    def _cold_event(self, ev: dict) -> None:
        if len(self.cold_events) < 512:
            self.cold_events.append(dict(ev))

    def _log_transition(self, key: str, lvl: int, tier_from: str,
                        tier_to: str, rows: int, cause: str) -> None:
        k = (tier_from, tier_to, cause)
        self.residency_stats[k] = self.residency_stats.get(k, 0) + 1
        if len(self.residency_log) < 4096:  # bounded; stats stay exact
            self.residency_log.append(
                {"node": key, "level": int(lvl), "tier_from": tier_from,
                 "tier_to": tier_to, "rows": int(rows), "cause": cause})

    def _leveled_nodes(self):
        for cn in self.cnodes:
            if isinstance(cn, cnodes._Leveled):
                st = self.states.get(str(cn.node.index))
                if st is not None and isinstance(st, tuple) and \
                        len(st) == 2 and isinstance(st[0], tuple):
                    yield cn, str(cn.node.index), st

    def tier_rows_by_node(self) -> Dict[str, Dict[str, int]]:
        """Per-trace resident row CAPACITY per tier, ONE walk over the
        leveled nodes (metrics scrapes and bench sampling index this
        instead of re-walking per key)."""
        out: Dict[str, Dict[str, int]] = {}
        for cn, k, (levels, _b) in self._leveled_nodes():
            row = {res.TIER_DEVICE: 0, res.TIER_HOST: 0, res.TIER_DISK: 0}
            tiers = self._tiers.get(k) or [res.TIER_DEVICE] * len(levels)
            for lvl, t in zip(levels, tiers):
                row[t] += lvl.cap
            out[k] = row
        return out

    def tier_rows(self, key: Optional[str] = None) -> Dict[str, int]:
        """Resident row CAPACITY per tier over the leveled traces (one
        trace when ``key`` given) — the compiled analog of
        ``Spine.tier_rows``; what the residency gauges and the growth
        bench sample."""
        out = {res.TIER_DEVICE: 0, res.TIER_HOST: 0, res.TIER_DISK: 0}
        for k, row in self.tier_rows_by_node().items():
            if key is not None and k != key:
                continue
            for t, rows in row.items():
                out[t] += rows
        return out

    def device_resident_rows(self, key: Optional[str] = None) -> int:
        """Device-resident leveled-trace capacity — what the device budget
        bounds (the residency hard-cap tests read this)."""
        return self.tier_rows(key)[res.TIER_DEVICE]

    def _split_states(self) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """(hot pytree, cold operand dict) for one step dispatch. The hot
        dict rides the donated argument; cold levels ride separately so
        XLA device_puts them per call (transient buffers) and the program
        never returns them as persistent outputs."""
        if not self._tiers:
            return self.states, {}
        hot = dict(self.states)
        cold: Dict[str, Any] = {}
        for key, tiers in self._tiers.items():
            if all(t == res.TIER_DEVICE for t in tiers):
                continue
            levels, base = hot[key]
            cold[key] = {str(i): levels[i]
                         for i, t in enumerate(tiers)
                         if t != res.TIER_DEVICE}
            hot[key] = (tuple(l for i, l in enumerate(levels)
                              if tiers[i] == res.TIER_DEVICE), base)
        return hot, cold

    @staticmethod
    def _interleave(hot_levels, lvmap) -> tuple:
        """THE one cold-level reinsertion rule (hot levels in order, cold
        levels at their recorded STR indices — pytree dict keys) — shared
        by the traced merge, the post-step rejoin, and the snapshot
        restore so the three paths can never reassemble different
        layouts."""
        K = len(hot_levels) + len(lvmap)
        it = iter(hot_levels)
        return tuple(lvmap[str(i)] if str(i) in lvmap else next(it)
                     for i in range(K))

    def _rejoin_states(self, states: Dict[str, Any],
                       cold: Dict[str, Any]) -> Dict[str, Any]:
        """Reassemble full level tuples after a step: program outputs for
        hot levels, the SAME host-side batch objects for cold ones (cold
        batches are immutable — the program never donates them)."""
        for key, lvmap in cold.items():
            if key not in states:
                continue
            hot_levels, base = states[key]
            states[key] = (self._interleave(hot_levels, lvmap), base)
        return states

    @staticmethod
    def _with_cold(states, cold):
        """(traced) merge cold operands back into full level tuples."""
        if not cold:
            return states
        out = dict(states)
        for key, lvmap in cold.items():
            hot, base = out[key]
            out[key] = (CompiledHandle._interleave(hot, lvmap), base)
        return out

    @staticmethod
    def _without_cold(new_states, cold):
        """(traced) strip cold levels from the returned states so they
        never become persistent program outputs."""
        for key, lvmap in (cold or {}).items():
            if key not in new_states:
                continue
            full, base = new_states[key]
            hot = tuple(l for i, l in enumerate(full)
                        if str(i) not in lvmap)
            new_states[key] = (hot, base)
        return new_states

    def _promote_level(self, cn, key: str, levels: list, tiers: list,
                       k: int, cause: str) -> None:
        """Promote one level to device for a WRITE (maintain drains merge
        into it). Disk levels take the VERIFIED read (the corruption
        detection point — recovery + incident via the cold store)."""
        t = tiers[k]
        if t == res.TIER_DEVICE:
            return
        if t == res.TIER_DISK:
            ent = self._cold_meta.get(key, {}).get(k)
            if ent is not None and ent.get("batch") is levels[k]:
                # meta dropped only AFTER the verified read succeeds — a
                # ColdError mid-promotion must leave the level tracked so
                # a retry still verifies instead of reading the memmap raw
                levels[k] = res.fault_batch(ent["blob"], self._store())
                self._cold_meta.get(key, {}).pop(k, None)
                self._store().release(ent["blob"])
            else:
                # IDENTITY mismatch (the save path's `batch is lvl` guard,
                # applied to the runtime promote): an overflow restore can
                # rewind a level to an OLDER disk batch than the recorded
                # meta describes — faulting through the stale meta would
                # merge the wrong content into the replay. Reconstruct the
                # meta from the memmap's content-addressed filenames and
                # VERIFY; the stale entry (if any) stays until its own
                # batch reappears or _sync_tiers drops it.
                levels[k] = res.fault_batch(
                    res.meta_from_batch(levels[k]), self._store())
        levels[k] = res.to_device(levels[k])
        tiers[k] = res.TIER_DEVICE
        self._lru[(key, k)] = self._interval
        self._log_transition(key, k, t, res.TIER_DEVICE, levels[k].cap,
                             cause)

    def _enforce_residency(self, cause: str = "budget") -> bool:
        """Demote/promote deep trace levels until every leveled trace fits
        the configured budgets. Called between validated intervals only
        (maintain / restore / config) — a tier change alters the hot
        pytree STRUCTURE, which the jitted step re-traces and caches per
        structure (an oscillating layout reuses its program; only
        capacity grows drop _step_jit). Policy: deepest-first demotion
        (deep levels are
        re-merged the least — one move buys the most headroom), level 0
        never demotes (the step program writes it every tick), and a host
        level only demotes to disk after ``lru_intervals`` maintain
        intervals without a write; promotion back to device happens for
        recently-written levels when headroom exists (the LRU clock —
        drain-writes promote eagerly in :meth:`maintain` itself)."""
        cfg = self.residency_cfg
        if cfg is None or not cfg.active or self.mesh is not None:
            return False
        changed = False
        for cn, key, (levels, base) in list(self._leveled_nodes()):
            K = len(levels)
            if K < 2 or getattr(cn, "_gc_refresh", False):
                continue
            tiers = list(self._tiers.get(key) or [res.TIER_DEVICE] * K)
            if len(tiers) != K:
                tiers = (tiers + [res.TIER_DEVICE] * K)[:K]
            levels = list(levels)

            def rows_in(tier):
                return sum(l.cap for l, t in zip(levels, tiers)
                           if t == tier)

            if cfg.device_rows is not None:
                for k in range(K - 1, 0, -1):
                    if rows_in(res.TIER_DEVICE) <= cfg.device_rows:
                        break
                    if tiers[k] != res.TIER_DEVICE:
                        continue
                    levels[k] = res.to_host(levels[k])
                    tiers[k] = res.TIER_HOST
                    self._log_transition(key, k, res.TIER_DEVICE,
                                         res.TIER_HOST, levels[k].cap,
                                         cause)
                    changed = True
            if cfg.host_rows is not None:
                for k in range(K - 1, 0, -1):
                    if rows_in(res.TIER_HOST) <= cfg.host_rows:
                        break
                    if tiers[k] != res.TIER_HOST:
                        continue
                    if self._interval - self._lru.get((key, k), -1 << 30) \
                            < cfg.lru_intervals:
                        continue  # recently written: not cold yet
                    lvl, meta = res.demote_batch_to_disk(levels[k],
                                                         self._store())
                    self._cold_meta.setdefault(key, {})[k] = {
                        "blob": meta, "batch": lvl}
                    levels[k] = lvl
                    tiers[k] = res.TIER_DISK
                    self._log_transition(key, k, res.TIER_HOST,
                                         res.TIER_DISK, lvl.cap, cause)
                    changed = True
            if cfg.device_rows is not None:
                # promotion under headroom, re-hot levels only (LRU)
                for k in range(1, K):
                    if tiers[k] != res.TIER_HOST:
                        continue
                    if self._interval - self._lru.get((key, k), -1 << 30) \
                            > cfg.lru_intervals:
                        continue  # cold: stays put
                    if rows_in(res.TIER_DEVICE) + levels[k].cap > \
                            cfg.device_rows:
                        continue
                    levels[k] = res.to_device(levels[k])
                    tiers[k] = res.TIER_DEVICE
                    self._log_transition(key, k, res.TIER_HOST,
                                         res.TIER_DEVICE, levels[k].cap,
                                         "lru")
                    changed = True
            if any(t != res.TIER_DEVICE for t in tiers):
                self._tiers[key] = tiers
            else:
                self._tiers.pop(key, None)
            cn.residency_tiers = tuple(tiers)
            self.states[key] = (tuple(levels), base)
        if changed:
            # a tier change alters the hot-pytree STRUCTURE only — the
            # jitted step re-traces and caches per input structure, so an
            # oscillating layout (drain promotes, budget demotes back)
            # re-uses its compiled program instead of recompiling; only
            # CAPACITY changes (grow) must drop _step_jit
            self._note_cause("residency")
        return changed

    def _sync_tiers(self, cause: str = "restore") -> None:
        """Reconcile the tier map with the ACTUAL leaf types after a path
        that may have materialized levels (restore re-padding after a
        grow) — the bookkeeping must never claim a tier the arrays left."""
        for cn, key, (levels, _b) in self._leveled_nodes():
            # DEFAULT to all-device rather than skipping untracked keys:
            # an overflow restore can reinsert a snapshot's cold level
            # under a tier map a later promotion emptied — skipping here
            # would leave the bookkeeping claiming "device" while the
            # leaf is a numpy/memmap batch, and the next dispatch would
            # ride it through the DONATED hot pytree (re-materializing
            # the whole level on device, unverified)
            tiers = self._tiers.get(key) or [res.TIER_DEVICE] * len(levels)
            tiers = (list(tiers) + [res.TIER_DEVICE] * len(levels)
                     )[:len(levels)]
            for k, lvl in enumerate(levels):
                actual = res.batch_tier(lvl)
                if actual != tiers[k]:
                    self._log_transition(key, k, tiers[k], actual,
                                         lvl.cap, cause)
                    tiers[k] = actual
                if actual != res.TIER_DISK:
                    ent = self._cold_meta.get(key, {}).pop(k, None)
                    if ent is not None:
                        self._store().release(ent["blob"])
            if any(t != res.TIER_DEVICE for t in tiers):
                self._tiers[key] = tiers
            else:
                self._tiers.pop(key, None)
            cn.residency_tiers = tuple(tiers)

    def _reconcile_cold_meta(self) -> None:
        """Re-key the disk blob bookkeeping to the ACTUAL batch objects
        after a rewind: an overflow restore can bring back an OLDER disk
        batch than the recorded meta describes (the meta followed a
        promote/re-demote cycle the snapshot predates). Stale entries are
        released; untracked disk levels get metas reconstructed from
        their content-addressed filenames (and re-retained, so the sweep
        cannot delete blobs the rewound state still needs)."""
        for cn, key, (levels, _b) in self._leveled_nodes():
            for k, lvl in enumerate(levels):
                ent = self._cold_meta.get(key, {}).get(k)
                is_disk = isinstance(lvl.weights, np.memmap)
                if ent is not None and ent.get("batch") is not lvl:
                    self._cold_meta[key].pop(k)
                    self._store().release(ent["blob"])
                    ent = None
                if is_disk and ent is None:
                    blob = res.meta_from_batch(lvl)
                    self._store().retain(blob)
                    self._cold_meta.setdefault(key, {})[k] = {
                        "blob": blob, "batch": lvl}

    def _sweep_cold(self) -> None:
        """Delete zero-reference cold blobs. Called ONLY when a new
        snapshot supersedes the old one — the one point where no overflow
        replay can ever fault content older than the live snapshot."""
        if self._cold_store is not None:
            self._cold_store.sweep()

    # -- tracing -------------------------------------------------------------
    def _run_nodes(self, states, tick, feeds, cold=None):
        """The scheduler's eval sequence as a pure traced function (shared
        by the single-worker and SPMD step builders)."""
        if self._gen_fn is not None:
            raw = self._gen_fn(tick)
            feeds = {self._op_to_index[id(getattr(h, "_op", h))]: b
                     for h, b in raw.items()}
        # cold (host/disk-tier) levels rejoin their traces here: they are
        # per-call operands, device_put by XLA for the duration of the
        # call, and stripped from the returned states below so they never
        # become persistent device buffers
        states = self._with_cold(states, cold)
        ctx = _Ctx(feeds)
        ctx.states = states  # strict-output halves read their partner's
        values: Dict[int, Any] = {}
        new_states = {}
        for cn in self.cnodes:
            ins = [values[i] for i in cn.node.inputs]
            st = states.get(str(cn.node.index))
            st2, out = cn.eval(ctx, st, ins)
            if st2 is not None:
                new_states[str(cn.node.index)] = st2
            values[cn.node.index] = out
        for idx, bound in ctx.gc_bounds.items():
            key = str(idx)
            if key in new_states:  # a leveled trace: truncate every level
                levels, base = new_states[key]
                # base_live goes stale-high until the next maintenance
                # recomputes it — conservative for capacity requirements
                new_states[key] = (tuple(
                    cnodes.truncate_below(lvl, bound)
                    for lvl in levels), base)
        new_states = self._without_cold(new_states, cold)
        req = (jnp.stack(ctx.reqs) if ctx.reqs
               else jnp.zeros((0,), jnp.int64))
        self._checks = ctx.req_index  # same order every trace
        return new_states, ctx.outputs, req

    def _make_step(self):
        # states are DONATED: levels past 0 (and any untouched state) flow
        # through the program unmodified, and donation lets XLA alias them
        # input->output instead of copying — without it every tick paid a
        # full copy of all trace state (~tens of MB at q4 scale, measured
        # as the dominant steady-state cost). The flip side: snapshots
        # must be real copies (see snapshot()).
        if self.mesh is None:
            def step_fn(states, tick, feeds, cold):
                ns, outs, req = self._run_nodes(states, tick, feeds, cold)
                # tick+1 rides the program output so the next dispatch
                # reuses a device-resident cursor (no per-tick h2d upload)
                return ns, outs, req, tick + 1

            return jax.jit(step_fn, donate_argnums=(0,))

        # SPMD: ONE shard_map around the whole eval sequence. Inside, every
        # batch is its [cap_local] worker slice, operators run their plain
        # single-worker kernels, and exchange/unshard nodes are the only
        # cross-worker communication (all_to_all / all_gather over the mesh
        # axis) — the reference's worker/exchange architecture as a single
        # SPMD program (shard.rs:35-101, exchange.rs:586).
        from dbsp_tpu.parallel.mesh import shard_map
        from jax.sharding import PartitionSpec as P

        from dbsp_tpu.parallel.mesh import WORKER_AXIS

        W = P(WORKER_AXIS)

        def step_fn(states, tick, feeds, cold):
            # cold is always empty under a mesh (residency is single-
            # worker only — see set_residency); the arg keeps the call
            # signature uniform across both builders
            def body(states_l, tick_l, feeds_l):
                squeeze = lambda t: jax.tree_util.tree_map(  # noqa: E731
                    lambda a: a[0], t)
                expand = lambda t: jax.tree_util.tree_map(  # noqa: E731
                    lambda a: a[None], t)
                new_states, outputs, req = self._run_nodes(
                    squeeze(states_l), tick_l, squeeze(feeds_l))
                return expand(new_states), expand(outputs), req[None]

            ns, outs, reqw = shard_map(
                body, mesh=self.mesh, in_specs=(W, P(), W),
                out_specs=(W, W, W))(states, tick, feeds)
            # tick+1 computed OUTSIDE the shard_map: tick is replicated, so
            # the cursor output needs no worker axis
            return ns, outs, jnp.max(reqw, axis=0), tick + 1

        return jax.jit(step_fn, donate_argnums=(0,))

    def _make_scan(self, n: int):
        """A jitted program running ``n`` ticks of the eval sequence inside
        one ``lax.scan`` — ONE dispatch (and one host round-trip, if the
        caller blocks) per n ticks. Over a tunneled accelerator a cached
        single-tick dispatch still costs ~1.5s of RPC overhead; scanning
        amortizes it to ~1.5s/n. Requirements reduce to a running max across
        iterations; outputs are the LAST tick's (carried, not stacked — no
        n-times memory blowup). gen_fn mode only (feeds are host values).

        Sharded circuits scan INSIDE the shard_map: the whole n-tick loop is
        one SPMD program whose collectives (exchange/gather/pmax) run per
        iteration — N ticks per dispatch at any worker count."""
        assert self._gen_fn is not None, "scan mode needs a gen_fn"

        def _scan_body(states, t0, cold=None, varying=False):
            outs_shape = jax.eval_shape(
                lambda s, t: self._run_nodes(s, t, {}, cold)[1], states, t0)
            init_outs = jax.tree_util.tree_map(
                lambda sh: jnp.zeros(sh.shape, sh.dtype), outs_shape)
            if varying and hasattr(jax.lax, "pcast"):
                # inside shard_map the per-tick outputs are worker-varying;
                # the zero init must carry the same vma type or the scan
                # carry types mismatch. Older JAX (< varying-manual-axes)
                # has no pcast and no vma tracking — skip, the carry
                # already type-checks there.
                from dbsp_tpu.parallel.mesh import WORKER_AXIS

                init_outs = jax.tree_util.tree_map(
                    lambda a: jax.lax.pcast(a, (WORKER_AXIS,), to="varying"),
                    init_outs)

            def body(carry, i):
                st, _ = carry
                ns, outs, req = self._run_nodes(st, t0 + i, {}, cold)
                # states absent from ns (stateless ticks) carry through
                merged = {**st, **ns}
                return (merged, outs), req

            (ns, outs), reqs = jax.lax.scan(
                body, (states, init_outs), jnp.arange(n, dtype=jnp.int64))
            req = (jnp.max(reqs, axis=0) if reqs.shape[1]
                   else jnp.zeros((0,), jnp.int64))
            # t0+n: the device-resident tick cursor for the next chunk
            return ns, outs, req, t0 + n

        if self.mesh is None:
            return jax.jit(_scan_body, donate_argnums=(0,))

        from dbsp_tpu.parallel.mesh import shard_map
        from jax.sharding import PartitionSpec as P

        from dbsp_tpu.parallel.mesh import WORKER_AXIS

        W = P(WORKER_AXIS)

        def scan_fn(states, t0, cold):
            def body(states_l, t0_l):
                squeeze = lambda t: jax.tree_util.tree_map(  # noqa: E731
                    lambda a: a[0], t)
                expand = lambda t: jax.tree_util.tree_map(  # noqa: E731
                    lambda a: a[None], t)
                ns, outs, req, _ = _scan_body(squeeze(states_l), t0_l,
                                              varying=True)
                return expand(ns), expand(outs), req[None]

            ns, outs, reqw = shard_map(
                body, mesh=self.mesh, in_specs=(W, P()),
                out_specs=(W, W, W))(states, t0)
            # cursor computed outside the shard_map (t0 is replicated)
            return ns, outs, jnp.max(reqw, axis=0), t0 + n

        return jax.jit(scan_fn, donate_argnums=(0,))

    def step_scanned(self, t0: int, n: int, block: bool = False) -> None:
        """Run ticks [t0, t0+n) as one scanned dispatch (see _make_scan).
        Programs are cached per chunk length n."""
        cache = getattr(self, "_scan_jits", None)
        if cache is None:
            cache = self._scan_jits = {}
        fn = cache.get(n)
        if fn is None:
            fn = cache[n] = self._make_scan(n)
        t_start = time.perf_counter_ns()
        hot, cold = self._split_states()
        with self._guard():
            states, outputs, req, tick_next = fn(
                hot, self._tick_operand(t0), cold)
        self._tick_dev, self._tick_host = tick_next, t0 + n
        self.states = self._rejoin_states(states, cold)
        self.last_outputs = outputs
        self._req = req if self._req is None else self._max_jit(self._req, req)
        if block:
            self.block()
        self._append_sample(time.perf_counter_ns() - t_start)

    # -- stepping ------------------------------------------------------------
    def _tick_operand(self, tick: int):
        """The device-resident tick scalar for ``tick``. Steady state: the
        previous dispatch already returned it (tick+1 / t0+n is a program
        output) — zero transfers. Discontinuities (first tick, restore,
        overflow replay, manual jumps) upload EXPLICITLY via device_put,
        which jax.transfer_guard("disallow") permits; what the guard
        convicts is the implicit per-tick jnp.asarray(tick) this replaced."""
        if self._tick_dev is None or self._tick_host != tick:
            self._tick_dev = jax.device_put(np.int64(tick))
            self._tick_host = tick
        return self._tick_dev

    def _guard(self):
        """The transfer-guard context for the jitted step/scan call — a
        no-op unless testing/retrace.py's sentinel armed _steady_guard."""
        if self._steady_guard is None:
            return contextlib.nullcontext()
        return jax.transfer_guard(self._steady_guard)

    def _note_cause(self, cause: str) -> None:
        """Annotate the NEXT latency sample with a spike cause (maintain /
        snapshot / retrace) — consumed by :meth:`_append_sample`."""
        self._pending_causes.add(cause)

    def _append_sample(self, ns: int) -> None:
        idx = len(self.step_times_ns)
        self.step_times_ns.append(ns)
        if self._pending_causes:
            for c in sorted(self._pending_causes):
                self.tick_causes.append((idx, c))
            self._pending_causes.clear()

    def reset_timing(self) -> None:
        """Clear latency samples, cause annotations, host-overhead records,
        and maintain stats (harnesses call this between warmup and the
        measured run, so reported slices/rows describe the measured window,
        not warmup's presize-era cascades)."""
        self.step_times_ns.clear()
        self.tick_causes.clear()
        self._pending_causes.clear()
        for v in self.host_overhead_ns.values():
            v.clear()
        for k in self.maintain_stats:
            self.maintain_stats[k] = 0

    def _dispatch(self, tick: int, feeds: Optional[Dict] = None) -> None:
        """Dispatch one tick's program asynchronously (no timing, no sync)."""
        if self._step_jit is None:
            self._note_cause("retrace")  # first call compiles the program
            self._step_jit = self._make_step()
        f = self._feed_indices(feeds) if feeds else {}
        hot, cold = self._split_states()
        with self._guard():
            states, outputs, req, tick_next = self._step_jit(
                hot, self._tick_operand(tick), f, cold)
        self._tick_dev, self._tick_host = tick_next, tick + 1
        self.states = self._rejoin_states(states, cold)
        self.last_outputs = outputs
        self._req = req if self._req is None else self._max_jit(self._req, req)

    def step(self, tick: int = 0, feeds: Optional[Dict] = None,
             block: bool = False) -> None:
        """Dispatch one tick. No host sync unless ``block``; call
        :meth:`validate` (one sync) before trusting outputs/state."""
        t0 = time.perf_counter_ns()
        self._dispatch(tick, feeds)
        if block:
            self.block()
        self._append_sample(time.perf_counter_ns() - t0)

    def _run_pipelined(self, t0: int, upto: int) -> None:
        """Run ticks [t0, upto) with a depth-1 pipeline: dispatch tick t,
        then wait for tick t-1 — host-side work (feed indexing, pytree
        flattening, dispatch) of one tick overlaps device compute of the
        previous one, replacing the old block-per-tick protocol that
        serialized host and device. One latency sample per iteration
        (dispatch of t + completion wait of t-1): on a backend where the
        donating step call is effectively synchronous (measured on this
        CPU PJRT client: a donated dispatch blocks until its input
        buffers' producer finishes) the sample IS tick t's latency; on a
        truly async backend it is tick t-1's, shifted by one. The
        interval's LAST tick completes inside the caller's validate()
        fetch — the designated sync point — and its wall time lands in
        ``host_overhead_ns["validate"]``."""
        prev = None
        t_prev = time.perf_counter_ns()
        for tt in range(t0, upto):
            self._dispatch(tt)
            # completion marker for THIS tick: the requirement running-max
            # (outputs when the circuit has no capacity checks) — outputs
            # and req are program results, never donated, so a held marker
            # stays valid across the next dispatch
            marker = self._req if self._req is not None else self.last_outputs
            if prev is not None:
                jax.block_until_ready(prev)  # hotpath: ok pipeline barrier on tick t-1
            now = time.perf_counter_ns()
            self._append_sample(now - t_prev)
            t_prev = now
            prev = marker

    def block(self) -> None:
        """Wait for dispatched work (cheap sync, no data transfer)."""
        jax.block_until_ready(self.states)

    # -- validation / growth -------------------------------------------------
    def validate(self) -> None:
        """ONE device->host fetch: check every capacity requirement recorded
        since the last validation. Raises :class:`CompiledOverflow`."""
        if self._req is None or not self._checks:
            return
        req = np.asarray(jax.device_get(self._req))
        items = []
        for (cn, key), r in zip(self._checks, req):
            cn.note_requirement(key, int(r))
            if int(r) > cn.caps[key]:
                items.append((cn, key, int(r)))
        self.last_req = req  # validated requirement levels (for presize)
        self._req = jnp.zeros_like(self._req)
        if items:
            raise CompiledOverflow(items)

    def _req_value(self, cn: CNode, key: str) -> Optional[int]:
        """The last validated requirement for (cn, key), if any."""
        if getattr(self, "last_req", None) is None:
            return None
        for (c, k), r in zip(self._checks, self.last_req):
            if c is cn and k == key:
                return int(r)
        return None

    def maintain(self, budget_rows: Optional[int] = None) -> bool:
        """Host-side spine maintenance: drain half-full trace levels into
        the next level, between validated intervals (the compiled-mode
        analog of the reference's background spine merger,
        spine_fueled.rs:1-81 — there fuel amortizes merges across steps;
        here the step program never touches levels past 0 at all, and this
        method runs the actual merges outside the hot program, one native
        two-pointer merge each).

        State stays VALID throughout (rows only move between levels whose
        union is the trace), so no replay is needed — but a receiving
        level's capacity may grow, which invalidates the compiled programs
        (next step re-traces). Returns True when that happened.

        Drain policy (the LSM discipline): a level is due when half-full;
        draining into a receiver that would itself become due cascades the
        receiver onward FIRST, so chains terminate at the tail — the only
        level whose capacity this method normally grows. Growing middle
        levels instead would quietly absorb every cascade: the tail would
        never compact and the middle of the ladder would balloon toward
        the tail's size.

        ``budget_rows`` (default: module :data:`MAINTAIN_BUDGET_ROWS`, env
        ``DBSP_TPU_MAINTAIN_BUDGET_ROWS``; None/<=0 = unbounded) bounds the
        rows MOVED between levels per call — the fuel. A level whose live
        rows exceed the remaining budget drains a prefix slice
        (:func:`_drain_slice`, the resumable cursor) and the rest stays
        due, resuming on the next call, so a full cascade amortizes over
        several intervals instead of landing in one tick. Deferral is
        always safe: the trace is the union of its levels at every point,
        so consumers see identical content (proven bit-identical by
        tests/test_maintenance.py); only compaction, not correctness, is
        deferred. Two carve-outs keep deferral from regressing into worse
        failure modes: level 0's drain is budget-EXEMPT (deferring it
        risks an overflow replay + retrace, and its slice is bounded by
        l0's capacity — one interval's inflow), and a budgeted drain whose
        receiver lacks room FILLS the receiver to its existing capacity
        instead of growing it (a mid-run middle-level grow would retrace
        the step program)."""
        from dbsp_tpu.circuit.runtime import Runtime

        if budget_rows is None:
            budget_rows = MAINTAIN_BUDGET_ROWS
        left = budget_rows if budget_rows and budget_rows > 0 else None
        stats = self.maintain_stats
        stats["calls"] += 1
        rows_before = stats["rows_moved"]
        self.maintain_pending = False
        self._interval += 1  # the residency LRU clock ticks per maintain
        changed = False
        prev_rt = Runtime._swap(self.runtime) if self.mesh is not None \
            else None
        try:
            for cn in self.cnodes:
                if not isinstance(cn, cnodes._Leveled):
                    continue
                key = str(cn.node.index)
                st = self.states.get(key)
                if st is None:
                    continue
                levels, base = st
                K = len(levels)
                if K == 1:
                    continue
                levels = list(levels)
                tiers = list(self._tiers.get(key)
                             or [res.TIER_DEVICE] * K)
                # Host-cached live counts: fetching them from the device
                # would dispatch one eager O(cap) reduction per level per
                # trace per interval (measured as a double-digit share of
                # steady-state time at q4 scale). Level 0 is the only
                # level the step program writes, and its validated
                # REQUIREMENT is exactly its live count at validation —
                # already fetched. Deeper levels only change in this
                # method, which maintains the cache (drain sums are upper
                # bounds — netting may shrink the real count; an over-
                # estimate only triggers an early drain, never an error).
                cache = getattr(cn, "_live_cache", None)
                if cache is None or len(cache) != K or \
                        getattr(cn, "_gc_refresh", False):
                    cache = [int(b.max_worker_live()) for b in levels]
                lives = cache
                req = self._req_value(cn, cn.level_keys[0])
                due0 = lives[0]
                if req is not None:
                    due0 = req
                    if getattr(cn, "_slot_cap", None):
                        # SLOTTED l0: the l0 requirement is slot CAPACITY
                        # consumed, not rows — using it as a row count
                        # would inflate every downstream lives[] (sparse
                        # deltas occupy whole slots) and burn the drain
                        # budget on phantom rows. The ROW count comes from
                        # the TAIL requirement (base + l0 live rows) minus
                        # the known deep lives; capacity still drives the
                        # drain-due check (full slots must fold even when
                        # sparsely filled).
                        tail_req = self._req_value(cn, cn.TAIL_KEY)
                        if tail_req is not None:
                            lives[0] = max(0, tail_req - sum(lives[1:]))
                        else:
                            lives[0] = req
                    else:
                        lives[0] = req
                # dispatch-free fast path: with cached lives the drain-due
                # check is host arithmetic — most intervals touch nothing
                # (l0's due check uses its consumed CAPACITY, see above)
                dues = [due0] + lives[1:]
                if not any(dues[k] and dues[k] * 2 >= levels[k].cap
                           for k in range(K - 1)):
                    cn._live_cache = lives
                    continue
                vers = self._level_versions.setdefault(key, [0] * K)

                def drain(k, exempt=False):
                    nonlocal changed, left
                    # l0 is budget-exempt: deferring IT is not a deferred
                    # compaction but an overflow REPLAY + step-program
                    # retrace (measured: a 17s p99 tick), and its slice is
                    # bounded by l0's capacity — one interval's inflow
                    budgeted = left is not None and not exempt and k > 0
                    if not budgeted and left is None and k + 1 < K - 1 and \
                            (lives[k] + lives[k + 1]) * 2 > levels[k + 1].cap:
                        # unbounded mode: make room downstream first (the
                        # budgeted path instead fills receivers to capacity
                        # and lets the shallow-first sweep drain them)
                        drain(k + 1)
                    n = min(lives[k], left) if budgeted else lives[k]
                    if n <= 0:
                        self.maintain_pending = True  # fuel ran out
                        return
                    # a drain WRITES both sides: cold operands promote to
                    # device first (disk reads verified — the compiled
                    # engine's corruption-detection point); the budget
                    # re-demotes after the sweep. A structure-only change
                    # — the jitted step re-traces per input structure, so
                    # no program invalidation is needed here.
                    if tiers[k] != res.TIER_DEVICE or \
                            tiers[k + 1] != res.TIER_DEVICE:
                        self._promote_level(cn, key, levels, tiers, k,
                                            "maintain")
                        self._promote_level(cn, key, levels, tiers, k + 1,
                                            "maintain")
                    rk1 = cn.level_keys[k + 1]
                    need = lives[k + 1] + n
                    if need > cn.caps[rk1]:
                        if k + 1 == K - 1:
                            # tail growth: unavoidable — the tail holds the
                            # whole trace (presize projects it to end-of-run
                            # size precisely to keep this out of the run)
                            cn.caps[rk1] = bucket_cap(need)
                            changed = True
                        elif left is None:
                            # unbounded mode: legacy headroom growth (an
                            # inverted ladder after l0 outgrew a middle
                            # level) — receivers absorb further drains
                            cn.caps[rk1] = bucket_cap(need * 2)
                            changed = True
                        else:
                            # budgeted: growing a middle level invalidates
                            # the step program (measured: a ~10-20s q4
                            # recompile landing in ONE tick). Fill the
                            # receiver to its existing capacity instead —
                            # the shallow-first sweep (or the next call)
                            # drains it onward; the remainder stays here.
                            n = cn.caps[rk1] - lives[k + 1]
                            if k == 0 and n < lives[k]:
                                # last resort: l0 MUST drain FULLY — a
                                # residue plus the next interval's inflow
                                # overflows l0 (replay + retrace). Force
                                # room below regardless of budget (rare;
                                # beats the overflow replay it prevents).
                                stats["exempt_drains"] += 1
                                drain(k + 1, exempt=True)
                                n = cn.caps[rk1] - lives[k + 1]
                            if n <= 0:
                                self.maintain_pending = True
                                return
                            n = min(n, lives[k])
                            need = lives[k + 1] + n
                    if k == 0 and getattr(cn, "_slot_cap", None):
                        # slotted l0: fold the per-slot sorted runs into
                        # one consolidated batch (rank-merge regime) so
                        # the drain merge sees its sorted-input contract;
                        # the step program's l0 aux stays untagged, so
                        # re-tag the emptied level after the drain
                        slot = cn._slot_cap
                        levels[0] = levels[0].tagged(
                            (slot,) * (levels[0].cap // slot)).consolidate()
                    if n >= lives[k]:
                        levels[k + 1], levels[k] = _drain_pair(
                            levels[k + 1], levels[k], cn.caps[rk1])
                        if k == 0:
                            # the step program's l0 aux is always None
                            levels[0] = levels[0].tagged(None)
                        stats["drains"] += 1
                    else:
                        levels[k + 1], levels[k] = _drain_slice(
                            levels[k + 1], levels[k],
                            jnp.asarray(n, jnp.int32), cn.caps[rk1])
                        if k == 0:
                            levels[0] = levels[0].tagged(None)
                        stats["partial_drains"] += 1
                        self.maintain_pending = True  # remainder stays due
                    vers[k] += 1
                    vers[k + 1] += 1
                    self._lru[(key, k)] = self._interval
                    self._lru[(key, k + 1)] = self._interval
                    lives[k + 1] += n  # upper bound (netting may shrink)
                    lives[k] -= n
                    stats["rows_moved"] += n
                    stats["max_slice_rows"] = max(stats["max_slice_rows"], n)
                    if budgeted:
                        stats["max_budgeted_slice_rows"] = max(
                            stats["max_budgeted_slice_rows"], n)
                        left -= n

                # Order: unbounded keeps the legacy deep-first cascade
                # (receivers make room before their feeders). Budgeted
                # runs SHALLOW-first — fill-to-cap makes draining into a
                # full receiver safe, and the sweep reaches that receiver
                # next, so the inflow path (l0 -> l1) can never starve
                # behind a multi-interval tail compaction; the deep,
                # state-sized drains get whatever fuel remains and defer
                # across calls.
                order = range(K - 1) if left is not None \
                    else range(K - 2, -1, -1)
                for k in order:
                    due = dues[0] if k == 0 else lives[k]
                    if due and due * 2 >= levels[k].cap:
                        if k > 0 and left is not None and left <= 0:
                            self.maintain_pending = True
                            continue  # deep compaction defers; l0 may not
                        drain(k)
                cn._live_cache = lives
                if any(t != res.TIER_DEVICE for t in tiers):
                    self._tiers[key] = tiers
                else:
                    self._tiers.pop(key, None)
                cn.residency_tiers = tuple(tiers)
                base_val = sum(lives[1:])
                self.states[key] = (tuple(levels),
                                    jnp.full_like(base, base_val))
        finally:
            if self.mesh is not None:
                Runtime._swap(prev_rt)
        # budget enforcement between intervals: demote what the drains
        # re-heated (and anything newly over budget), promote re-hot
        # levels under headroom — every transition logged with its cause
        changed |= self._enforce_residency(cause="budget")
        if stats["rows_moved"] > rows_before:
            self._note_cause("maintain")
        if changed:
            self._note_cause("retrace")
            self._step_jit = None
            self._scan_jits = {}
        return changed

    def _enforce_ladders(self) -> bool:
        """Re-establish geometric level capacities between l0 and the tail.

        Requirement-driven growth sizes l0 (per-interval inflow) and the
        tail (whole-trace projection) but says nothing about the middle
        levels; without this they collapse toward l0's size and every
        drain cascades straight into the tail (observed: an all-32768
        ladder under a 1M tail merging the tail every ~4 ticks)."""
        changed = False
        for cn in self.cnodes:
            if not isinstance(cn, cnodes._Leveled):
                continue
            keys = cn.level_keys
            if len(keys) < 3:
                continue
            lo, hi = cn.caps[keys[0]], cn.caps[keys[-1]]
            if hi <= lo:
                continue
            g = (hi / lo) ** (1.0 / (len(keys) - 1))
            for k in range(1, len(keys) - 1):
                target = bucket_cap(int(lo * g ** k))
                if target > cn.caps[keys[k]]:
                    cn.caps[keys[k]] = target
                    changed = True
        return changed

    def presize(self, ratio: float, safety: float = 1.3,
                interval: int = 1) -> None:
        """Scale capacities for a run ~``ratio``x longer than what produced
        the last validated requirements: monotone capacities (traces, group
        gathers — they integrate the stream) are projected linearly; stable
        ones (join fan-outs — per-delta) just get doubled headroom. One
        re-trace now instead of a grow/replay ladder mid-measurement.

        ``interval`` is the validation cadence of the RUN being presized
        for: a leveled trace's level 0 only drains at validation points
        (maintain), so it must hold ``interval`` ticks of inflow — warmup
        validates every tick, making its observed l0 requirement a
        per-tick figure that would otherwise overflow (and grow/replay)
        on the first measured interval."""
        if getattr(self, "last_req", None) is None:
            return
        changed = False
        for (cn, key), r in zip(self._checks, self.last_req):
            r = int(r)
            if r <= 0:
                continue
            is_l0 = isinstance(cn, cnodes._Leveled) and \
                len(cn.level_keys) > 1 and key == cn.level_keys[0]
            if is_l0:
                target = int(r * max(1, interval) * safety)
            elif key in cn.MONOTONE_CAPS:
                target = int(r * ratio * safety)
            else:
                target = 2 * r
            if bucket_cap(target) > cn.caps[key]:
                cn.caps[key] = bucket_cap(target)
                changed = True
        changed |= self._enforce_ladders()
        if changed:
            snap = self.snapshot()
            self._step_jit = None
            self._scan_jits = {}
            self._req = None
            self.restore(snap)  # re-pad states to the new capacities
        self.prewarm_maintenance()

    def prewarm_maintenance(self) -> None:
        """Compile the maintenance drain kernels for the CURRENT ladder
        shapes, on warmup's clock instead of the measured run's.

        Each (receiver cap, source cap, out cap, schema) combination of
        :func:`_drain_pair` / :func:`_drain_slice` compiles on first use;
        left to happen lazily, those compiles land inside the measured
        window the first time each level pair drains (measured: ~5s of
        q4's mini-run maintain overhead was drain-kernel compiles, dwarfing
        the drains themselves). Presize fixes the ladder for the planned
        run, so every pair can be compiled here by running one throwaway
        drain over COPIES of the live levels (donation consumes the
        copies, never the state; results are discarded)."""
        from dbsp_tpu.circuit.runtime import Runtime

        prev_rt = Runtime._swap(self.runtime) if self.mesh is not None \
            else None
        try:
            for cn in self.cnodes:
                if not isinstance(cn, cnodes._Leveled):
                    continue
                st = self.states.get(str(cn.node.index))
                if st is None or len(st[0]) < 2:
                    continue
                levels = st[0]
                for k in range(len(levels) - 1):
                    recv, src = levels[k + 1], levels[k]
                    if isinstance(recv.weights, np.ndarray) or \
                            isinstance(src.weights, np.ndarray):
                        # cold (demoted) pair: a real drain promotes it
                        # first — prewarming here would transfer the whole
                        # level just to warm a kernel cache
                        continue
                    cap = cn.caps[cn.level_keys[k + 1]]
                    if recv.cap != cap:
                        continue  # growth pending; shapes would not match
                    if k == 0 and getattr(cn, "_slot_cap", None):
                        # slotted l0 drains consolidate the slot runs
                        # first — warm that fold program (and the drain
                        # over its tagged result) too
                        slot = cn._slot_cap
                        src = _copy_tree(src).tagged(
                            (slot,) * (src.cap // slot)).consolidate()
                    _drain_pair(_copy_tree(recv), _copy_tree(src), cap)
                    if MAINTAIN_BUDGET_ROWS:
                        _drain_slice(_copy_tree(recv), _copy_tree(src),
                                     jnp.asarray(0, jnp.int32), cap)
        finally:
            if self.mesh is not None:
                Runtime._swap(prev_rt)

    def grow(self, overflow: CompiledOverflow, headroom: int = 2,
             project_ratio: float = 1.0) -> None:
        """Grow the overflowed capacities (with headroom, so a growing state
        doesn't re-overflow next interval) and force a re-trace.

        ``project_ratio`` > 1 folds the presize projection into the grow:
        monotone capacities (traces — they integrate the stream) jump
        straight to their projected end-of-run size. On a tunneled
        accelerator each re-trace costs a full program compile (~minutes),
        so one projected grow beats a doubling ladder by several compiles.

        State since the last validated snapshot is invalid — callers MUST
        follow with :meth:`restore` of a validated snapshot (which re-pads
        it to the new capacities)."""
        exchange_hit = False
        for cn, key, required in overflow.items:
            # exchange-bucket overflow: a skewed tick routed more rows to a
            # worker than the static per-worker capacity — the replay that
            # follows is the data-loss save; count it (obs + bench export).
            # Per-KIND detection counts each overflowed site; the handle's
            # exchange_overflows counts REPLAYS (once per grow, matching
            # overflow_replays' unit even when one interval overflows
            # several exchange buckets).
            if isinstance(cn, cnodes.CExchange) or \
                    (isinstance(cn, cnodes.CInput) and key == "input"):
                from dbsp_tpu.parallel.exchange import count_exchange_overflow

                count_exchange_overflow(
                    "exchange" if isinstance(cn, cnodes.CExchange)
                    else "input")
                exchange_hit = True
            factor = max(headroom, project_ratio * 1.3) \
                if key in cn.MONOTONE_CAPS else headroom
            # max: a capacity key can overflow at several sites in one
            # interval (e.g. one requirement per trace level) — never let a
            # later, smaller item shrink the grown cap
            cn.caps[key] = max(cn.caps[key],
                               bucket_cap(int(required * factor)))
        if exchange_hit:
            self.exchange_overflows += 1
        self._enforce_ladders()
        self._step_jit = None
        self._scan_jits = {}
        self._req = None

    def _snap_cacheable(self, key: str):
        """The leveled cnode for ``key`` if its deep levels are
        copy-skippable (untouched between maintain calls), else None.
        Window-GC'd traces are excluded: the step program truncates EVERY
        level in-program each tick, so their deep levels are never clean."""
        cn = self.by_index.get(int(key))
        if isinstance(cn, cnodes._Leveled) and \
                not getattr(cn, "_gc_refresh", False):
            st = self.states.get(key)
            if isinstance(st, tuple) and len(st) == 2 and \
                    isinstance(st[0], tuple) and len(st[0]) > 1:
                return cn
        return None

    def snapshot(self) -> Dict[str, Any]:
        """A restorable DEEP copy of the current (validated) states.

        Step programs donate their state buffers (input->output aliasing
        is what keeps untouched trace levels copy-free per tick), so a
        reference snapshot would be invalidated by the very next step —
        the copy here is the price of in-place stepping, paid per
        snapshot interval instead of per tick.

        INCREMENTAL: the step program only ever writes level 0 of a
        leveled trace — deeper levels change solely in :meth:`maintain`
        (version-counted there). A deep level whose version matches the
        cached copy from a previous snapshot reuses that copy instead of
        being copied again, so steady-state snapshot cost is O(level 0 +
        small states), not O(whole trace). Cached copies are plain result
        buffers (never donated anywhere — :meth:`restore` copies before
        use), so sharing them across snapshots is safe."""
        to_copy: Dict[str, Any] = {}
        reuse: Dict[str, Dict[int, Batch]] = {}
        for key, st in self.states.items():
            cn = self._snap_cacheable(key)
            if cn is None:
                to_copy[key] = st
                continue
            levels, b = st
            vers = self._level_versions.setdefault(key, [0] * len(levels))
            cache = self._snap_levels.get(key) or [None] * len(levels)
            kept: Dict[int, Batch] = {}
            fresh: Dict[int, Batch] = {}
            for i, lvl in enumerate(levels):
                if i > 0 and isinstance(lvl.weights, np.ndarray):
                    # cold (host/disk) level: immutable host-side buffers
                    # the program never donates — share by reference
                    # instead of copying through the device
                    kept[i] = lvl
                    continue
                ent = cache[i] if i > 0 else None
                if ent is not None and ent[0] == vers[i]:
                    kept[i] = ent[1]
                else:
                    fresh[i] = lvl
            to_copy[key] = (fresh, b)
            reuse[key] = kept
        copied = _copy_tree(to_copy)  # ONE dispatch for every fresh leaf
        snap: Dict[str, Any] = {}
        for key, st in self.states.items():
            if key not in reuse:
                snap[key] = copied[key]
                continue
            levels, _ = st
            fresh_c, base_c = copied[key]
            vers = self._level_versions[key]
            cache = self._snap_levels.setdefault(
                key, [None] * len(levels))
            merged = []
            for i in range(len(levels)):
                if i in reuse[key]:
                    merged.append(reuse[key][i])
                else:
                    merged.append(fresh_c[i])
                    if i > 0:
                        cache[i] = (vers[i], fresh_c[i])
            snap[key] = (tuple(merged), base_c)
        return snap

    def restore(self, snap: Dict[str, Any]) -> None:
        """Restore a snapshot (copying again — the snapshot must survive
        the restored states being donated), re-padding trace states to the
        current capacities (no-op when capacities haven't changed)."""
        from dbsp_tpu.circuit.runtime import Runtime

        # cold (numpy/memmap) levels in the snapshot are immutable host
        # buffers: reinsert them by reference instead of device-copying
        # them through _copy_tree (which would re-materialize every
        # demoted level on device during an overflow replay)
        snap2: Dict[str, Any] = {}
        cold_ref: Dict[str, Dict[str, Batch]] = {}
        for key, st in snap.items():
            if isinstance(st, tuple) and len(st) == 2 and \
                    isinstance(st[0], tuple):
                levels, base = st
                holds = {str(i): l for i, l in enumerate(levels)
                         if isinstance(l.weights, np.ndarray)}
                if holds:
                    cold_ref[key] = holds
                    snap2[key] = (tuple(l for i, l in enumerate(levels)
                                        if str(i) not in holds), base)
                    continue
            snap2[key] = st
        states = _copy_tree(snap2)
        for key, holds in cold_ref.items():
            hot, base = states[key]
            states[key] = (self._interleave(hot, holds), base)
        # the restored buffers are new objects at possibly new capacities;
        # drop the deep-level copy cache and advance every version so a
        # later snapshot never pairs a stale copy with the rewound state
        self._snap_levels.clear()
        for vers in self._level_versions.values():
            for i in range(len(vers)):
                vers[i] += 1
        # repad may consolidate a slotted l0 (slot geometry can change with
        # the grown capacities) — on sharded states that is an SPMD program
        # needing this handle's runtime
        prev_rt = Runtime._swap(self.runtime) if self.mesh is not None \
            else None
        try:
            for cn in self.cnodes:
                key = str(cn.node.index)
                if key in states:
                    states[key] = cn.repad_state(states[key])
                # cached live counts may UNDER-estimate the rewound state
                # (drains moved rows since the snapshot) — maintain() must
                # refetch exact counts or its drain could slice live rows
                cn._live_cache = None
        finally:
            if self.mesh is not None:
                Runtime._swap(prev_rt)
        self.states = states
        # re-padding after a grow may have materialized cold levels on
        # device (with_cap is a jnp op): reconcile the tier map with the
        # actual leaf types AND the blob bookkeeping with the actual
        # batch objects, then re-demote anything over budget
        self._sync_tiers(cause="restore")
        self._reconcile_cold_meta()
        self._enforce_residency(cause="restore")

    # -- checkpointed run -----------------------------------------------------
    def run_ticks(self, t0: int, n: int, validate_every: int = 16,
                  on_validated: Optional[Callable] = None,
                  block_each: bool = False, scan: bool = False,
                  project_ratio: float = 1.0,
                  snapshot_every: int = 1,
                  maintain_budget_rows: Optional[int] = None) -> None:
        """Run ticks [t0, t0+n) under a ``gen_fn`` with periodic validation
        and snapshot/replay on overflow (exact: inputs are functions of the
        tick index). ``on_validated(next_tick)`` fires after each validated
        interval with EXACTLY-ONCE delivery per reported tick: a high-water
        mark suppresses re-fires while an overflow replay re-runs intervals
        since the last snapshot (``snapshot_every > 1``), so accumulating
        callbacks (throughput counters) stay correct across replays.

        ``block_each`` runs each interval PIPELINED at depth 1 (see
        :meth:`_run_pipelined`): tick t+1's host work overlaps tick t's
        device compute, and ``step_times_ns`` records the wall time between
        consecutive tick completions — a real per-tick latency distribution
        without the old sync-per-tick serialization. Without it, ticks
        dispatch fully async and the only syncs are the validation
        fetches at interval boundaries.

        ``scan=True`` runs each validation interval as ONE scanned dispatch
        (see :meth:`step_scanned`) — per-tick latency is then the chunk time
        / chunk length. ``project_ratio`` is handed to :meth:`grow` so an
        overflow mid-run jumps monotone capacities to end-of-run size.
        ``maintain_budget_rows`` bounds each interval's maintenance slice
        (see :meth:`maintain`); between-tick host phases are timed into
        ``host_overhead_ns`` and annotated onto the next latency sample."""
        assert self._gen_fn is not None, "run_ticks needs a gen_fn"
        overhead = self.host_overhead_ns
        h0 = time.perf_counter_ns()
        snap, snap_t = self.snapshot(), t0
        overhead["snapshot"].append(time.perf_counter_ns() - h0)
        t = t0
        iv = 0
        reported = t0  # high-water tick already delivered to on_validated
        while t < t0 + n:
            upto = min(t + validate_every, t0 + n)
            if scan:
                self.step_scanned(t, upto - t, block=block_each)
            elif block_each:
                self._run_pipelined(t, upto)
            else:
                for tt in range(t, upto):
                    self.step(tick=tt)
            h0 = time.perf_counter_ns()
            try:
                self.validate()
            except CompiledOverflow as e:
                overhead["validate"].append(time.perf_counter_ns() - h0)
                self.overflow_replays += 1
                if any(isinstance(cn, cnodes.CExchange) or
                       (isinstance(cn, cnodes.CInput) and k == "input")
                       for cn, k, _ in e.items):
                    # skew past a static per-worker bucket: the replay IS
                    # the no-data-loss path; attribute it distinctly so
                    # flight/incident evidence separates exchange growth
                    # from ordinary trace-capacity growth
                    self._note_cause("exchange_overflow")
                self.grow(e, project_ratio=project_ratio)
                self.restore(snap)
                self._note_cause("retrace")
                t = snap_t
                continue  # replay from the snapshot at the new capacities
            overhead["validate"].append(time.perf_counter_ns() - h0)
            h0 = time.perf_counter_ns()
            # state stays valid; may re-trace next step
            self.maintain(budget_rows=maintain_budget_rows)
            overhead["maintain"].append(time.perf_counter_ns() - h0)
            iv += 1
            t = upto
            if iv % max(1, snapshot_every) == 0:
                # snapshots copy level 0 + the small states (deep levels
                # reuse version-matched cached copies, see snapshot()) —
                # coarser cadence amortizes them further; the replay window
                # on a rare overflow widens accordingly, which determinism
                # makes exact either way
                h0 = time.perf_counter_ns()
                snap, snap_t = self.snapshot(), t
                overhead["snapshot"].append(time.perf_counter_ns() - h0)
                self._sweep_cold()  # old snapshot superseded: safe point
                self._note_cause("snapshot")
            if on_validated is not None and t > reported:
                # replayed intervals (t <= reported after an overflow
                # rewind) were already delivered — suppress the duplicate
                on_validated(t)
                reported = t

    # -- host views -----------------------------------------------------------
    def canonicalize_sink(self, b):
        """Canonical form of a (possibly deferred) sink batch: the ONE
        deferred-to-sink consolidation policy shared by :meth:`output` and
        the serving driver's flush. No-op for non-batches and for batches
        already known-canonical (1 sorted run); sharded batches
        canonicalize per worker under this handle's runtime."""
        if not isinstance(b, Batch) or b.sorted_runs == 1:
            return b
        if b.sharded:
            from dbsp_tpu.circuit.runtime import Runtime

            prev = Runtime._swap(self.runtime)
            try:
                return b.consolidate()
            finally:
                Runtime._swap(prev)
        return b.consolidate()

    # -- operator attribution (EXPLAIN ANALYZE) -------------------------------
    def profile_ticks(self, n: int = 8, t0: int = 0,
                      feeds_list=None, spans=None,
                      registry=None) -> dict:
        """Measured per-node attribution: run ``n`` ticks with the step
        split into per-node jit segments (wall time + rows per node),
        assert the segmented run bit-identical to the fused program, and
        REWIND — production state and counters are untouched (see
        :mod:`dbsp_tpu.obs.opprofile` for the protocol and its caveats).
        ``t0`` is the tick index to profile from (matters under a
        ``gen_fn``: inputs are functions of the tick). Returns the shared
        ``/profile`` report (``opprofile.PROFILE_SCHEMA``)."""
        from dbsp_tpu.obs.opprofile import ProfileError, measured_profile

        if any(t != res.TIER_DEVICE for ts in self._tiers.values()
               for t in ts):
            raise ProfileError(
                "segmented profiling requires fully device-resident "
                "states: residency-demoted levels would be re-transferred "
                "per segment and the attribution would time the tiering, "
                "not the operators — raise DBSP_TPU_DEVICE_ROWS or "
                "profile an unbudgeted twin")

        return measured_profile(self, n=n, t0=t0, feeds_list=feeds_list,
                                spans=spans, registry=registry)

    def profile_static(self, feeds: Optional[Dict] = None) -> dict:
        """Compile-time attribution: per-node XLA cost analysis (flops /
        analytic bytes — the ROOFLINE §1 accounting applied per node)
        joined with graph metadata. No timing, no state mutation."""
        from dbsp_tpu.obs.opprofile import static_profile

        return static_profile(self, feeds=feeds)

    def output(self, handle_or_op) -> Optional[Batch]:
        """Latest output batch for an output handle (device; un-fetched).

        Deferred-to-sink canonicalization: when the placement pass removed
        a consolidation from the program, the sink batch arrives as a known
        multi-run or raw batch — canonicalize it HERE, lazily, on actual
        read (the hot loop never reads outputs, so the work only happens
        when a consumer exists). Already-canonical batches (1 sorted run)
        pass through untouched, so non-deferred pipelines see the identical
        object."""
        op = getattr(handle_or_op, "_op", handle_or_op)
        idx = self._op_to_index[id(op)]
        b = self.last_outputs.get(idx)
        canon = self.canonicalize_sink(b)
        if canon is not b:
            # cache the canonical batch so repeat reads of the same tick's
            # output (polling HTTP clients) don't re-consolidate
            self.last_outputs[idx] = canon
        return canon


def compile_circuit(handle, gen_fn: Optional[Callable] = None,
                    verified: bool = False) -> CompiledHandle:
    """Compile a host :class:`~dbsp_tpu.circuit.runtime.CircuitHandle`'s
    circuit. Existing operator state (spines warmed by host-path steps)
    migrates into the compiled states — warm up host-side, then compile.

    Multi-worker circuits (built with ``Runtime.init_circuit(N, ...)``)
    compile to a single SPMD program over the runtime's mesh; in that case a
    ``gen_fn`` runs per-worker inside the program and may use
    ``jax.lax.axis_index("workers")`` to generate its slice."""
    from dbsp_tpu.analysis import verify_circuit
    from dbsp_tpu.circuit.runtime import Runtime

    rt = getattr(handle, "runtime", None)
    # static analysis before tracing: an ERROR circuit (dangling feedback,
    # mismatched join keys, missing shard) would compile fine and produce
    # wrong answers; refusing here costs one graph walk. ``verified=True``
    # skips it for callers (the manager) that already ran verify_circuit —
    # avoids double-logging every WARN at deploy.
    if not verified:
        verify_circuit(handle.circuit,
                       workers=rt.workers if rt is not None else 1)
    prev = Runtime._swap(rt)
    try:
        ch = CompiledHandle(handle.circuit, gen_fn=gen_fn, runtime=rt)
    finally:
        Runtime._swap(prev)
    # retrace-sentinel construction hook (one flag check when disabled):
    # under DBSP_TPU_RETRACE_SENTINEL=1 / retrace.session() the handle's
    # program builders are ledgered and its transfer guard armed
    from dbsp_tpu.testing import retrace as _retrace_sentinel

    _retrace_sentinel.maybe_watch(ch)
    return ch
