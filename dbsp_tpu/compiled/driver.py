"""Run a production (manager/controller) pipeline on the compiled path.

The reference keeps a JIT facade precisely so SQL-originated pipelines run
its compiled backend (``crates/dataflow-jit/src/facade.rs:48,105`` —
``DbspCircuit::new`` builds the jitted dataflow, ``step`` feeds it); without
it every deployed pipeline would fall back to the interpreted path. This is
that facade for the XLA backend: :class:`CompiledCircuitDriver` duck-types
the one method the IO controller calls (``step``) while running each tick
through :class:`~dbsp_tpu.compiled.compiler.CompiledHandle` — one XLA
program per tick instead of per-operator dispatches.

Feed/overflow protocol: inputs arrive through the normal host
``InputHandle`` buffers (the catalog's ``push_rows``); each ``step`` drains
them via ``ZSetInput.eval`` (same canonicalization as the host path),
runs the tick, and validates capacity requirements at the validation
cadence. On overflow it grows, restores the interval-start snapshot, and
replays the retained feeds — deterministic, so the replay is exact.

Validation cadence (``DBSP_TPU_SERVE_VALIDATE_EVERY``, default 1): at 1,
every tick snapshots, validates, and delivers immediately — the bounded-
replay contract serving pipelines shipped with. At N > 1 the driver
PIPELINES: ticks dispatch asynchronously (JAX async dispatch lets the host
encode of tick t+1 — the input drain — overlap device compute of tick t),
feeds are retained for replay, and outputs buffer until the interval
validates, then deliver in order. One snapshot + one device fetch per N
ticks instead of per tick; output visibility lags up to N-1 ticks.

Outputs flow back through the host ``OutputOperator.eval`` so every
existing consumer (HTTP ``/read`` cursors, output transports, ``to_dict``
tests) sees compiled and host pipelines identically.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time
from typing import Dict, List, Optional, Tuple

from dbsp_tpu.compiled.compiler import (CompiledHandle, CompiledOverflow,
                                        compile_circuit)

logger = logging.getLogger(__name__)


def enable_compile_cache(path: Optional[str] = None) -> Optional[str]:
    """Wire JAX's persistent compilation cache for compiled pipelines.

    ``path`` (or env ``DBSP_TPU_COMPILE_CACHE_DIR``) names an on-disk cache
    directory; every XLA program the engine traces (step programs, scan
    chunks, drain kernels) is serialized there and reused across process
    restarts — a q4 warmup measured 37 s cold against a 3.1 s measured
    window (BENCH r05), and all of it is retrace/recompile that a warm
    cache eliminates. No-op (returns None) when unset, so default deploys
    keep JAX's stock behavior. Thresholds are zeroed so every program is
    cached: engine programs are many and individually small."""
    path = path or os.environ.get("DBSP_TPU_COMPILE_CACHE_DIR")
    if not path:
        return None
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except AttributeError:  # knob renamed/absent on this jax version
            logger.debug("compile-cache knob %s unavailable", knob)
    return path


class CompiledCircuitDriver:
    """Controller-facing driver over a compiled circuit (see module doc)."""

    mode = "compiled"
    spans = None  # optional obs.SpanRecorder (set by CompiledInstrumentation)

    def __init__(self, handle, compiled: Optional[CompiledHandle] = None,
                 validate_every: Optional[int] = None):
        from dbsp_tpu.operators.io_handles import OutputOperator, ZSetInput
        from dbsp_tpu.operators.upsert import UpsertInput

        self.host_handle = handle
        self.circuit = handle.circuit
        enable_compile_cache()  # DBSP_TPU_COMPILE_CACHE_DIR, if set
        self.ch = compiled or compile_circuit(handle)
        self._tick = 0
        self.validate_every = max(1, validate_every if validate_every
                                  is not None else int(os.environ.get(
                                      "DBSP_TPU_SERVE_VALIDATE_EVERY", "1")))
        # (op, drain_fn): ZSetInput feeds its tick batch; UpsertInput feeds
        # the raw command batch its compiled node diffs against state
        self._inputs = []
        for cn in self.ch.cnodes:
            if isinstance(cn.op, ZSetInput):
                self._inputs.append((cn.op, cn.op.eval))
            elif isinstance(cn.op, UpsertInput):
                self._inputs.append((cn.op, cn.op.take_commands))
        self._outputs = [(cn.node.index, cn.op) for cn in self.ch.cnodes
                         if isinstance(cn.op, OutputOperator)]
        # interval state: snapshot at interval start, retained (tick, feeds)
        # for exact replay, buffered per-tick outputs awaiting validation
        self._snap = None
        self._retained: List[Tuple[int, Dict]] = []
        self._out_buffer: List[Dict[int, object]] = []
        # wall-time the current deferred-validation interval opened (first
        # retained tick) — None when no interval is open. Drives the
        # /status ``open_interval_age_s`` freshness surface.
        self._interval_open_ts: Optional[float] = None

    @property
    def step_latencies_ns(self):
        return self.ch.step_times_ns

    @property
    def interval_open(self) -> bool:
        """True while ticks sit in an unvalidated interval — their outputs
        are not yet visible to readers (cadence > 1 only)."""
        return bool(self._retained)

    @property
    def open_interval_age_s(self) -> Optional[float]:
        """Seconds since the open deferred-validation interval started, or
        None when every delivered tick has validated (interval closed)."""
        ts = self._interval_open_ts
        return None if ts is None else max(0.0, time.time() - ts)

    def step(self) -> None:
        """One serving tick: drain input buffers -> compiled step ->
        (at the validation cadence) validate, grow + exact replay of the
        retained interval on overflow, maintain, and deliver the buffered
        outputs to the host output operators."""
        feeds: Dict = {op: drain() for op, drain in self._inputs}
        spans = self.spans
        if spans is not None:
            spans.begin(f"tick[{self._tick}]", cat="step")
        if not self._retained:
            # interval-start checkpoint; timed into host_overhead_ns like
            # run_ticks does, so serving pipelines feed the same phase
            # observability (obs histogram + flight recorder) as bench runs
            h0 = time.perf_counter_ns()
            self._snap = self.ch.snapshot()
            self.ch.host_overhead_ns["snapshot"].append(
                time.perf_counter_ns() - h0)
            # the previous interval's snapshot is gone: zero-reference
            # cold blobs can be swept without endangering any replay
            self.ch._sweep_cold()
            self._interval_open_ts = time.time()
        self._retained.append((self._tick, feeds))
        with (spans.span("compiled_step", cat="compiled") if spans
              is not None else contextlib.nullcontext()):
            self.ch.step(tick=self._tick, feeds=feeds)
        # feeds are host-built program INPUTS (never donated), so the
        # retained references replay the identical batches after a grow
        self._out_buffer.append(dict(self.ch.last_outputs))
        self._tick += 1
        if len(self._retained) >= self.validate_every:
            self._flush()
        if spans is not None:
            spans.end(f"tick[{self._tick - 1}]")

    def _flush(self) -> None:
        """Validate the open interval; on overflow grow + replay the
        retained feeds from the interval-start snapshot (exact); then run
        a bounded maintenance slice and deliver outputs in tick order."""
        spans = self.spans
        h0 = time.perf_counter_ns()
        while True:
            try:
                self.ch.validate()
                break
            except CompiledOverflow as e:
                self.ch.overflow_replays += 1
                if spans is not None:
                    spans.instant("overflow_replay", cat="compiled")
                self.ch.grow(e)
                self.ch.restore(self._snap)
                self._out_buffer.clear()
                for tick, feeds in self._retained:
                    self.ch.step(tick=tick, feeds=feeds)
                    self._out_buffer.append(dict(self.ch.last_outputs))
        self.ch.host_overhead_ns["validate"].append(
            time.perf_counter_ns() - h0)
        h0 = time.perf_counter_ns()
        self.ch.maintain()  # spine drains; dispatch-free when nothing due
        self.ch.host_overhead_ns["maintain"].append(
            time.perf_counter_ns() - h0)
        for outputs in self._out_buffer:
            for idx, out_op in self._outputs:
                batch = outputs.get(idx)
                if batch is not None:
                    # deferred-to-sink consolidation (placement pass):
                    # canonicalize at delivery so every host consumer
                    # (HTTP readers, transports, to_dict tests) sees the
                    # same batches as the eager-consolidate engine — the
                    # ONE policy shared with CompiledHandle.output()
                    canon = self.ch.canonicalize_sink(batch)
                    if canon is not batch and \
                            self.ch.last_outputs.get(idx) is batch:
                        # share the canonical batch with output() readers
                        self.ch.last_outputs[idx] = canon
                    out_op.eval(canon)
        self._out_buffer.clear()
        self._retained.clear()
        self._snap = None
        self._interval_open_ts = None

    def flush(self) -> None:
        """Force validation/delivery of a partially-filled interval (the
        controller calls this on pause/stop and before barrier reads so a
        cadence > 1 never leaves undelivered ticks behind)."""
        if self._retained:
            self._flush()

    def profile_ticks(self, n: int = 8, spans=None, registry=None) -> dict:
        """Measured operator attribution at the driver's current position:
        flush the open deferred-validation interval (so the snapshot sits
        at a validated tick boundary), then run the segmented protocol —
        per-node timing, bit-identity assert, rewind — via
        :meth:`CompiledHandle.profile_ticks`. The caller owns quiescence:
        the ``/profile`` route invokes this under the controller's step
        lock so no serving tick is in flight.

        Workload: the open interval's retained feeds (captured BEFORE the
        flush clears them) replay as the profiled ticks' inputs, so a
        cadence > 1 pipeline profiles real recent deltas. At the default
        serve cadence of 1 nothing is retained and the profile runs EMPTY
        ticks — on a delta-proportional engine that attributes fixed
        per-node overhead, not the serving workload, and the report says
        so (``measured["idle_inputs"]``)."""
        feeds_list = [dict(f) for _, f in self._retained] or None
        self.flush()
        return self.ch.profile_ticks(n, t0=self._tick,
                                     feeds_list=feeds_list,
                                     spans=spans if spans is not None
                                     else self.spans, registry=registry)

    def residency_summary(self):
        """Tiered-residency digest of the compiled engine (per-tier rows,
        budgets, transition count) for ``/status`` — None when residency
        is unconfigured and nothing ever demoted. See
        :func:`dbsp_tpu.residency.summary`."""
        from dbsp_tpu import residency

        return residency.summary(self)

    def restore_checkpoint(self, tick: int, retained) -> None:
        """Resume from a restored checkpoint (dbsp_tpu.checkpoint): the
        engine states were already applied to ``self.ch`` at the
        checkpoint's validated tick; this replays the checkpoint's
        retained-feed window — the inputs of the open (not yet validated)
        interval — so the driver lands exactly where the checkpointed one
        stood, with the same buffered outputs awaiting validation.
        Exactly-once: retained ticks were never delivered pre-crash
        (delivery happens at validation), so the replay re-delivers
        nothing and re-runs everything, deterministically."""
        self._snap = None
        self._retained = []
        self._out_buffer = []
        self._interval_open_ts = None
        self._tick = int(tick)
        for t, feeds_by_idx in retained:
            feeds = {self.ch.by_index[i].op: b
                     for i, b in feeds_by_idx.items()}
            if not self._retained:
                self._snap = self.ch.snapshot()
                self._interval_open_ts = time.time()
            self._retained.append((t, feeds))
            self.ch.step(tick=t, feeds=feeds)
            self._out_buffer.append(dict(self.ch.last_outputs))
            self._tick = t + 1
        if len(self._retained) >= self.validate_every:
            self._flush()


def try_compiled_driver(handle, registry=None, verified=False, flight=None):
    """Compile the circuit if every operator has a compiled equivalent;
    None when it must stay on the host-driven path (the caller records
    which mode the pipeline runs — facade.rs's feature gate).

    ANY compile-time failure falls back: ``NotImplementedError`` is the
    designed signal (operator without a compiled node), but init_state()
    can also raise (e.g. ``AssertionError`` from CZ1Input for non-Batch
    feedback) — with compiled mode defaulting on for every manager
    pipeline, an unexpected compile error must degrade to the host
    scheduler that previously ran the circuit, not kill the deploy. The
    failure is logged and, when ``registry`` (obs.MetricsRegistry) is
    given, counted as ``dbsp_tpu_compiled_fallback_total{reason=...}``.

    ``flight`` (obs.FlightRecorder) additionally records the fallback as a
    structured event carrying the reason AND its human-readable detail —
    the host fallback is an order-of-magnitude perf cliff, so it must be
    SLO-visible (the watchdog latches it into a degraded state and an
    incident), not just a counter a dashboard may or may not chart."""
    from dbsp_tpu.analysis import AnalysisError

    try:
        if verified:
            return CompiledCircuitDriver(
                handle, compiled=compile_circuit(handle, verified=True))
        return CompiledCircuitDriver(handle)
    except AnalysisError:
        # a circuit that FAILS STATIC ANALYSIS is broken on every path —
        # falling back would run it on the host scheduler and produce the
        # wrong answers the analyzer exists to prevent
        raise
    except Exception as e:  # noqa: BLE001 — deliberate: fallback, not crash
        reason = type(e).__name__
        if isinstance(e, NotImplementedError):
            logger.debug("compiled driver unavailable: %s", e)
        else:
            logger.warning("compiled driver failed (%s: %s); falling back "
                           "to the host scheduler", reason, e)
        if registry is not None:
            registry.counter(
                "dbsp_tpu_compiled_fallback_total",
                "Circuits that failed to compile and fell back to the "
                "host-driven path", labels=("reason",)).labels(
                    reason=reason).inc()
        if flight is not None:
            flight.record("fallback", reason=reason, detail=str(e)[:200])
        return None
