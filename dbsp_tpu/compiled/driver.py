"""Run a production (manager/controller) pipeline on the compiled path.

The reference keeps a JIT facade precisely so SQL-originated pipelines run
its compiled backend (``crates/dataflow-jit/src/facade.rs:48,105`` —
``DbspCircuit::new`` builds the jitted dataflow, ``step`` feeds it); without
it every deployed pipeline would fall back to the interpreted path. This is
that facade for the XLA backend: :class:`CompiledCircuitDriver` duck-types
the one method the IO controller calls (``step``) while running each tick
through :class:`~dbsp_tpu.compiled.compiler.CompiledHandle` — one XLA
program per tick instead of per-operator dispatches.

Feed/overflow protocol: inputs arrive through the normal host
``InputHandle`` buffers (the catalog's ``push_rows``); each ``step`` drains
them via ``ZSetInput.eval`` (same canonicalization as the host path),
snapshots the compiled states, runs the tick, and validates capacity
requirements immediately. On overflow it grows, restores the snapshot, and
replays the SAME tick from the retained feeds — serving pipelines validate
every tick (the retained-feed window is one step), trading the benchmark
path's amortized validation for bounded replay.

Outputs flow back through the host ``OutputOperator.eval`` so every
existing consumer (HTTP ``/read`` cursors, output transports, ``to_dict``
tests) sees compiled and host pipelines identically.
"""

from __future__ import annotations

import contextlib
import logging
from typing import Dict, Optional

from dbsp_tpu.compiled.compiler import (CompiledHandle, CompiledOverflow,
                                        compile_circuit)

logger = logging.getLogger(__name__)


class CompiledCircuitDriver:
    """Controller-facing driver over a compiled circuit (see module doc)."""

    mode = "compiled"
    spans = None  # optional obs.SpanRecorder (set by CompiledInstrumentation)

    def __init__(self, handle, compiled: Optional[CompiledHandle] = None):
        from dbsp_tpu.operators.io_handles import OutputOperator, ZSetInput
        from dbsp_tpu.operators.upsert import UpsertInput

        self.host_handle = handle
        self.circuit = handle.circuit
        self.ch = compiled or compile_circuit(handle)
        self._tick = 0
        # (op, drain_fn): ZSetInput feeds its tick batch; UpsertInput feeds
        # the raw command batch its compiled node diffs against state
        self._inputs = []
        for cn in self.ch.cnodes:
            if isinstance(cn.op, ZSetInput):
                self._inputs.append((cn.op, cn.op.eval))
            elif isinstance(cn.op, UpsertInput):
                self._inputs.append((cn.op, cn.op.take_commands))
        self._outputs = [(cn.node.index, cn.op) for cn in self.ch.cnodes
                         if isinstance(cn.op, OutputOperator)]

    @property
    def step_latencies_ns(self):
        return self.ch.step_times_ns

    def step(self) -> None:
        """One serving tick: drain input buffers -> compiled step ->
        validate (grow + exact same-tick replay on overflow) -> deliver
        outputs to the host output operators."""
        feeds: Dict = {op: drain() for op, drain in self._inputs}
        spans = self.spans
        if spans is not None:
            spans.begin(f"tick[{self._tick}]", cat="step")
        snap = self.ch.snapshot()
        while True:
            with (spans.span("compiled_step", cat="compiled") if spans
                  is not None else contextlib.nullcontext()):
                self.ch.step(tick=self._tick, feeds=feeds)
            try:
                self.ch.validate()
                break
            except CompiledOverflow as e:
                self.ch.overflow_replays += 1
                if spans is not None:
                    spans.instant("overflow_replay", cat="compiled")
                self.ch.grow(e)
                self.ch.restore(snap)
        self.ch.maintain()  # spine drains; dispatch-free when nothing due
        self._tick += 1
        for idx, out_op in self._outputs:
            batch = self.ch.last_outputs.get(idx)
            if batch is not None:
                out_op.eval(batch)
        if spans is not None:
            spans.end(f"tick[{self._tick - 1}]")


def try_compiled_driver(handle, registry=None, verified=False):
    """Compile the circuit if every operator has a compiled equivalent;
    None when it must stay on the host-driven path (the caller records
    which mode the pipeline runs — facade.rs's feature gate).

    ANY compile-time failure falls back: ``NotImplementedError`` is the
    designed signal (operator without a compiled node), but init_state()
    can also raise (e.g. ``AssertionError`` from CZ1Input for non-Batch
    feedback) — with compiled mode defaulting on for every manager
    pipeline, an unexpected compile error must degrade to the host
    scheduler that previously ran the circuit, not kill the deploy. The
    failure is logged and, when ``registry`` (obs.MetricsRegistry) is
    given, counted as ``dbsp_tpu_compiled_fallback_total{reason=...}``."""
    from dbsp_tpu.analysis import AnalysisError

    try:
        if verified:
            return CompiledCircuitDriver(
                handle, compiled=compile_circuit(handle, verified=True))
        return CompiledCircuitDriver(handle)
    except AnalysisError:
        # a circuit that FAILS STATIC ANALYSIS is broken on every path —
        # falling back would run it on the host scheduler and produce the
        # wrong answers the analyzer exists to prevent
        raise
    except Exception as e:  # noqa: BLE001 — deliberate: fallback, not crash
        reason = type(e).__name__
        if isinstance(e, NotImplementedError):
            logger.debug("compiled driver unavailable: %s", e)
        else:
            logger.warning("compiled driver failed (%s: %s); falling back "
                           "to the host scheduler", reason, e)
        if registry is not None:
            registry.counter(
                "dbsp_tpu_compiled_fallback_total",
                "Circuits that failed to compile and fell back to the "
                "host-driven path", labels=("reason",)).labels(
                    reason=reason).inc()
        return None
