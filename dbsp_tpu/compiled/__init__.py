"""Compiled circuit execution: the whole step as ONE jitted XLA program.

See :mod:`dbsp_tpu.compiled.compiler` for the design rationale.
"""

from dbsp_tpu.compiled.compiler import (CompiledHandle, CompiledOverflow,
                                        compile_circuit)

__all__ = ["CompiledHandle", "CompiledOverflow", "compile_circuit"]
