"""Pure-functional operator evals for compiled circuit execution.

Each compiled node (``C*`` class) mirrors one host operator class from
``dbsp_tpu/operators/`` but expresses its per-tick eval as a PURE function
``eval(ctx, state, inputs) -> (state', output)`` over static-capacity device
batches, so the scheduler's whole eval sequence can be traced into one XLA
program (see compiler.py). The algorithms are the same — the kernels are
literally shared with the host path (``_join_level_impl``,
``_reduce_groups_impl``, ...); what changes is the *driver*: grow-on-demand
host loops and per-eval ``device_get`` checks become static capacities plus
device-side "required capacity" scalars that the runner validates out of the
hot loop (reference analog: the dataflow-jit backend compiles circuits whose
shapes Rust generics would otherwise fix at compile time,
``crates/dataflow-jit/src/dataflow/mod.rs``).

State capacities live in ``self.caps`` (plain ints). Every eval registers its
requirements via ``ctx.require(self, cap_key, device_scalar)``; the runner
compares the running max of those scalars against the configured caps at
validation points and grows + retraces on overflow.

INPUT trace states (CTrace — the integrators consumers probe) are LEVELED
inside the program — the spine, compiled (reference: the fueled spine's
amortization contract, ``crates/dbsp/src/trace/spine_fueled.rs:1-81``).
Each trace is a static tuple of K level batches in geometric capacity
classes; a tick's delta lands in a SLOT of level 0 with one
dynamic-update-slice (O(|Δ|) copied bytes, no merge — see
``_Leveled._levels_append``), and deeper compaction happens between
validated intervals in host-driven maintenance — so per-tick HBM traffic
is O(Δ) and the merge work is amortized to one sorted-run fold per
interval instead of per tick.

Two design rules keep leveling from costing more than it saves (measured
on Nexmark q4, CPU backend — violating either regressed steady-state ~5x):

  * Consumers combine their K per-level probe results into ONE shared
    static buffer at running offsets (:func:`join_levels`,
    :func:`gather_levels`) and consolidate ONCE — sort volume stays
    O(out_cap), not O(K·out_cap), and the probes themselves are
    delta-proportional binary searches, so fan-out over levels is cheap.
  * OUTPUT traces (an aggregate's previous-outputs batch, a topk's, a
    linear aggregate's accumulators) are NOT leveled: consolidated they
    hold exactly one live row per key, so the old-value gather is an
    exact q_cap expansion. Leveled, a key's current value smears into
    un-netted insert/retract pairs across levels and the gather
    requirement grows with the RUN (observed: 98k rows gathered per tick
    for a 12.5k-event delta) — strictly worse than the single O(keys)
    merge they pay per tick.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from dbsp_tpu.zset import kernels
from dbsp_tpu.zset.batch import Batch, bucket_cap, concat_batches

# ---------------------------------------------------------------------------
# Static leveled trace (the in-program spine)
# ---------------------------------------------------------------------------

# Level count K (including the tail) and the default capacity ratio between
# adjacent levels. Level capacities self-scale to the observed delta size
# through the requirement/grow machinery; these only seed the ladder.
# Read at CTrace construction time (not import) so harnesses that know the
# planned run length can pick K before compiling — see levels_for_run().
# Clamped to >= 1: K=0 would make every levels fan-out (join_levels /
# gather_levels) trace over an empty sequence and fail obscurely.
TRACE_LEVELS = max(1, int(os.environ.get("DBSP_TPU_TRACE_LEVELS", "4")))
LEVEL0_CAP = int(os.environ.get("DBSP_TPU_TRACE_L0", "1024"))
# growth 4 measured 42% faster steady-state than 8 on Nexmark q4/CPU at the
# default protocol (11.5k vs 8.1k ev/s; p99 1.6s vs 2.0s; growth 3 within
# noise of 4): tighter capacity classes make each spill's merge cheaper
# without meaningfully increasing spill frequency
LEVEL_GROWTH = int(os.environ.get("DBSP_TPU_TRACE_GROWTH", "4"))


def lazy_post_enabled() -> bool:
    """LAZY post views: after a SLOTTED append, consumers probe the
    (consolidated) delta itself as one more ladder level instead of
    re-reading the freshly written level-0 slot — CTrace.eval stops being
    a materialization consumers wait on (the dynamic_update_slice's only
    remaining reader is the donated state carry, which XLA aliases in
    place). The Z-set a consumer sees is IDENTICAL — the written slot
    holds exactly the delta's rows — only the raw slot order of the fused
    consumers' (pre-consolidation) buffers changes, which every consumer
    canonicalizes away (CJoin consolidates, the reducers net, distinct
    reads ``pre``). ``DBSP_TPU_TRACE_LAZY_POST=0`` is the code-free A/B
    control (pairs with the ``DBSP_TPU_NATIVE`` per-kernel force-off)."""
    return os.environ.get("DBSP_TPU_TRACE_LAZY_POST", "1") != "0"


def levels_for_run(ticks: int) -> int:
    """Level count that amortizes tail merges for a planned run length.

    State ≈ ticks·Δ and L0 holds ~2 deltas, so with growth ratio g the tail
    absorbs a spill every ~2·g^(K-2) ticks; K ≈ log_g(ticks/8) deep levels
    keeps that to a handful per run. Short runs (few large batches) get
    K=1-2 — a K too high for the run length loses steady-state to spill
    overhead, and K too low loses to O(state) re-merges (BENCH round-4
    sweep, pre-slotting: K=1 2831 ev/s, K=2 4342, K=4 5231 at 96 ticks).

    Since the SLOTTED level 0 landed (one ladder of per-delta slots folded
    once per interval), l0 itself absorbs what the first deep level used
    to, so the formula carries one level less than the pre-slot tuning:
    re-measured on Nexmark q4/CPU at 100 ticks, K=3 beats K=4 on both p50
    (9.4 vs 9.8 ms) and elapsed (1.53 vs 1.62 s)."""
    import math

    if ticks <= 1:
        return 1
    extra = max(0.0, math.log(ticks / 8, LEVEL_GROWTH))
    return max(1, min(4, 1 + math.ceil(extra)))


class _Leveled:
    """Mixin managing a leveled static trace state: ``(levels, base_live)``
    where ``levels`` is a tuple of K consolidated batches (level 0 smallest,
    last = tail) and ``base_live`` is a device scalar carrying the frozen
    live-row count of levels 1..K-1. Capacity keys are "l0".."l{K-2}" plus
    the subclass's ``TAIL_KEY`` (which keeps its legacy name so
    MONOTONE_CAPS / presize semantics carry over unchanged).

    Spill scheduling is HOST-DRIVEN: the per-tick program only writes the
    delta into level 0 (a slot append — see :meth:`_levels_append`) and
    touches nothing else — levels 1..K-1 flow through the step unmodified,
    so XLA aliases them instead of copying. Draining level k into level k+1
    happens BETWEEN validated intervals in ``CompiledHandle.maintain()``
    (an earlier in-program ``lax.cond`` cascade copied every level's full
    capacity on every non-spill tick: measured ~10ms/tick per trace at q4
    state sizes — the reference runs its spine merges on background fuel
    for the same reason, spine_fueled.rs:1-81). Because only level 0
    changes inside an interval, ``base_live`` stays exact between
    maintenance points and the whole-trace size requirement (what presize's
    monotone projection keys off) costs one O(cap_l0) reduction per tick.
    """

    TAIL_KEY = "trace"

    def _init_level_caps(self) -> None:
        n = max(1, TRACE_LEVELS)
        self.level_keys: Tuple[str, ...] = tuple(
            f"l{k}" for k in range(n - 1)) + (self.TAIL_KEY,)
        cap = LEVEL0_CAP
        for key in self.level_keys[:-1]:
            self.caps.setdefault(key, bucket_cap(cap))
            cap *= LEVEL_GROWTH

    def _levels_init(self, schema, lead, migrated: Optional[Batch]):
        lv = [Batch.empty(*schema, cap=self.caps[k], lead=lead)
              for k in self.level_keys]
        # level 0's run tag is ALWAYS None: the slotted append produces an
        # untagged batch, and the tag is pytree AUX data — it must be
        # byte-identical at init, after appends, and across drains, or
        # scan carries mismatch and every tick retraces the step program
        lv[0] = lv[0].tagged(None)
        base = 0
        if migrated is not None:
            # warm start: the host spine's consolidated state becomes the tail
            lv[-1] = migrated.with_cap(self.caps[self.TAIL_KEY])
            base = int(migrated.max_worker_live())
        return (tuple(lv), jnp.full(lead, base, jnp.int64))

    def _levels_append(self, ctx, state, delta: Batch):
        """Append a delta to level 0 (the only in-program state write).

        SLOTTED append (the steady-state path): level 0 is a ladder of
        ``cap(l0) / cap(delta)`` static SLOTS of one delta capacity each.
        Appending writes the (padded, consolidated) delta into the next
        free slot with one ``dynamic_update_slice`` — O(|delta|) copied
        bytes, NO merge, NO O(cap) sentinel re-fill. The slot contents are
        sorted runs at STATIC offsets, so consumers probe them as extra
        ladder levels (:meth:`_view_levels`) and maintenance folds them
        with sorted merges once per interval instead of the step program
        merging every tick (measured ~1-1.6 ms per trace per tick at q4
        caps — the single largest per-tick cost after the fused cursors
        landed). Occupancy is DERIVED (count of non-empty slots — empty
        deltas re-use their slot), so the state layout is unchanged.
        Falls back to the legacy merge when the slot geometry doesn't hold
        (delta capacity not dividing l0) or the trace is window-GC'd
        (in-program truncation compacts across slot boundaries).

        Registers two requirements: level 0's consumed capacity (slots in
        use after this append x slot size — drained each maintenance
        interval, so its running max is the per-interval inflow) and the
        whole-trace size (base_live + level-0 rows) under ``TAIL_KEY`` —
        the monotone capacity presize projects linearly. When the slots
        are full, further rows land in the LAST slot (clobbered) and the
        capacity requirement exceeds cap — the runner's validation grows
        and replays, the standard overflow contract.
        """
        from jax import lax

        levels, base = state
        new = list(levels)
        l0 = new[0]
        dcap = delta.cap
        can_slot = (not getattr(self, "_gc_refresh", False)
                    and not getattr(self, "_no_slots", False)  # per-level
                    # consumers (range join / window / rolling) fan one
                    # launch per viewed level — see compiler.__init__
                    and len(self.level_keys) > 1  # K=1: l0 IS the tail —
                    # no maintenance drain would ever fold the slots
                    and l0.cap % dcap == 0
                    and delta.weights.ndim == l0.weights.ndim)
        # the slot size is PINNED per instance: geometry must describe the
        # CONTENT of l0, which survives across retraces — re-deriving it
        # from each trace's delta capacity would reinterpret slots written
        # at one size as sorted runs at another (unsorted garbage to every
        # fused probe). A delta whose capacity doesn't match the pin takes
        # the canonicalize-then-merge fallback below; its output (one
        # consolidated run) remains a valid slot ladder at ANY size, so
        # matching deltas resume slotting afterwards.
        if can_slot and getattr(self, "_slot_cap", None) is None:
            self._slot_cap = dcap
        slotted = can_slot and self._slot_cap == dcap
        # static per-trace decision consumed by the lazy post view (the
        # same inputs retrace to the same value, so the step program's
        # structure is stable across retraces)
        self._append_slotted = slotted
        if slotted:
            nslots = l0.cap // dcap
            w_slots = l0.weights.reshape(
                *l0.weights.shape[:-1], nslots, dcap)
            occ = jnp.sum(jnp.any(w_slots != 0, axis=-1), axis=-1)
            has = jnp.any(delta.weights != 0, axis=-1)
            start = (jnp.minimum(occ, nslots - 1) * dcap).astype(jnp.int32)
            # write ONLY when the delta has rows and a free slot exists: an
            # unconditional write would clobber the last occupied slot on
            # an empty delta at full occupancy (no overflow would fire —
            # the requirement stays == cap), silently losing rows. A full
            # ladder with a NON-empty delta also skips the write: its rows
            # are lost either way, but the capacity requirement then
            # exceeds cap and the runner replays from the snapshot.
            write = has & (occ < nslots)

            def put(dst, src):
                return jnp.where(
                    write,
                    lax.dynamic_update_slice_in_dim(
                        dst, src.astype(dst.dtype), start, axis=-1),
                    dst)

            l0_live = jnp.sum(l0.weights != 0) + jnp.sum(delta.weights != 0)
            new[0] = Batch(
                tuple(put(k, dk) for k, dk in zip(l0.keys, delta.keys)),
                tuple(put(v, dv) for v, dv in zip(l0.vals, delta.vals)),
                put(l0.weights, delta.weights))
            ctx.require(self, self.level_keys[0],
                        (occ + jnp.where(has, 1, 0)) * dcap)
            if self.TAIL_KEY != self.level_keys[0]:
                ctx.require(self, self.TAIL_KEY, base + l0_live)
            return (tuple(new), base)
        if getattr(self, "_slot_cap", None) is not None:
            # l0 may hold slot runs: canonicalize before the merge (whose
            # contract requires sorted inputs). Only mismatched-capacity
            # deltas pay this in-program sort; stable feeds never do.
            nk0 = len(l0.keys)
            cols0, w0 = kernels.consolidate_cols(l0.cols, l0.weights)
            l0 = Batch(cols0[:nk0], cols0[nk0:], w0)
        m0 = l0.merge_with(delta)
        live0 = m0.live_count()
        ctx.require(self, self.level_keys[0], live0)
        if self.TAIL_KEY != self.level_keys[0]:
            ctx.require(self, self.TAIL_KEY, base + live0)
        new[0] = m0.with_cap(self.caps[self.level_keys[0]]).tagged(None)
        return (tuple(new), base)

    def _view_levels(self, levels) -> Tuple[Batch, ...]:
        """The level tuple consumers probe: slotted level 0 expands into
        its per-slot runs (static slices, each a consolidated batch), the
        deeper levels pass through. The fused trace cursors fan over the
        whole expansion in one probe, so extra slots cost probe lanes, not
        kernel launches."""
        slot = getattr(self, "_slot_cap", None)
        l0 = levels[0]
        if not slot or l0.cap == slot or l0.cap % slot != 0:
            return tuple(levels)
        slices = tuple(
            Batch(tuple(k[..., i * slot:(i + 1) * slot] for k in l0.keys),
                  tuple(v[..., i * slot:(i + 1) * slot] for v in l0.vals),
                  l0.weights[..., i * slot:(i + 1) * slot], runs=(slot,))
            for i in range(l0.cap // slot))
        return (*slices, *levels[1:])

    def _levels_repad(self, state):
        levels, base = state
        # re-tag while re-padding: levels are consolidated by contract —
        # EXCEPT a slotted level 0, whose runs live at slot offsets (its
        # state rides untagged; maintain re-tags before folding). A
        # uniform tag per level keeps the state pytree aux byte-stable
        # across drains/restores (an aux change would retrace the step).
        out = []
        for i, (b, k) in enumerate(zip(levels, self.level_keys)):
            if i == 0 and getattr(self, "_slot_cap", None) is not None:
                # a SLOTTED l0 canonicalizes on restore: the grow that
                # preceded it may have changed the producer's delta
                # capacity, and the append path re-checks the pinned slot
                # size against a consolidated l0 safely (any contiguous
                # window of a consolidated region is itself a valid
                # sorted run at every slot size). Never-slotted traces
                # keep their l0 consolidated by construction — no sort.
                b = b.consolidate().with_cap(self.caps[k]).tagged(None)
            elif i == 0:
                b = b.with_cap(self.caps[k]).tagged(None)
            else:
                b = b.with_cap(self.caps[k]).tagged((self.caps[k],))
            out.append(b)
        return (tuple(out), base)


def static_append(trace: Batch, delta: Batch) -> Tuple[Batch, jnp.ndarray]:
    """Merge ``delta`` into a fixed-capacity SINGLE-batch trace.

    Returns (new trace at the SAME capacity, required live rows). Live rows
    pack to the front after a merge, so slicing back to the trace capacity
    drops only dead tail — unless required > cap, which the runner detects.
    This is the state layout for operator OUTPUT traces (one live row per
    key; see module doc for why those must not be leveled)."""
    merged = trace.merge_with(delta)
    required = merged.live_count()
    return merged.with_cap(trace.cap), required


def join_levels(delta: Batch, levels: Sequence[Batch], nk: int, fn,
                out_cap: int, sorted_emit=None) -> Tuple[Batch, jnp.ndarray]:
    """Join a delta against ALL trace levels into ONE out_cap buffer via the
    fused trace cursor (zset/cursor.py): one probe pair over the whole
    ladder and one cross-level expansion, where the per-level loop emitted
    K probe kernels, K expansions, and K offset-scatters. With a
    permutation pair fn (``sorted_emit`` — see ``JoinCore.sorted_emit``)
    the native path applies the fn IN the call and the buffer comes back
    as one consolidated run, so the post-join consolidate rank-folds
    instead of sorting. The returned requirement is the UNCLAMPED total
    across levels — when it exceeds ``out_cap`` the tail matches drop off
    the end and the runner's validation grows the cap and replays."""
    from dbsp_tpu.zset import cursor

    assert levels, "join_levels: trace has no levels (TRACE_LEVELS >= 1)"
    out, total = cursor.join_ladder(delta, levels, nk, fn, out_cap,
                                    sorted_emit)
    return out, total.astype(jnp.int64)


def gather_levels(qkeys, qlive, levels: Sequence[Batch], out_cap: int):
    """Gather the query keys' rows from ALL trace levels into ONE shared
    (qrow, vals, w) part of capacity ``out_cap`` via the fused trace cursor
    (one ladder probe pair + one cross-level expansion). Dead slots carry
    qrow == q_cap + sentinel vals. Returns (part, unclamped total). NOTE:
    with K > 1 the combined part may hold cross-level insert/retract rows
    for the same (qrow, vals) — reducers must net them
    (``_reduce_groups_impl(..., net=True)``)."""
    from dbsp_tpu.zset import cursor

    assert levels, "gather_levels: trace has no levels (TRACE_LEVELS >= 1)"
    part, total = cursor.gather_ladder(qkeys, qlive, levels, out_cap)
    return part, total.astype(jnp.int64)


def ensure_side_cap(cn: "CNode", key: str, floor: int) -> int:
    """Size a fused join consumer's shared output buffer lazily on FIRST
    eval (compile time knows no delta shapes) — the ONE sizing helper both
    join directions and the range join share. The floor lands on
    ``bucket_cap``'s power-of-two grow ladder: the old raw ``max(64,
    delta.cap)`` guess lived OFF the ladder the requirement-driven regrow
    (CompiledHandle.grow) climbs, so the first-tick guess and the
    ladder-total requirement could drift apart across the two directions
    (left at a raw 6900, right regrown to a bucketed 8192 — two different
    capacity vocabularies for one node's A/B and presize accounting)."""
    if not cn.caps.get(key):
        cn.caps[key] = bucket_cap(max(64, floor))
    return cn.caps[key]


def trim_queries(ctx, cn: "CNode", qkeys, qlive):
    """Slice the (front-packed) unique-key buffer down to the "queries"
    capacity, requirement-checked. The compiled analog of the host path's
    ``_unique_keys`` re-bucketing (aggregate.py:211): every downstream
    gather/reduce/diff in the aggregate family is sized by this buffer, so
    leaving it at delta capacity drags delta-sized kernels through evals
    that touch few groups (a 21-group GROUP BY under a 32k-cap delta)."""
    if not cn.caps.get("queries"):
        cn.caps["queries"] = 64
    q_cap = cn.caps["queries"]
    ctx.require(cn, "queries", jnp.sum(qlive))
    return tuple(c[..., :q_cap] for c in qkeys), qlive[..., :q_cap]


@dataclasses.dataclass
class CView:
    """Compiled analog of ``operators.trace_op.TraceView``: the trace of a
    stream before (z^-1) and after this tick's append. ``pre``/``post`` are
    the LEVEL TUPLES of the leveled trace state — consumers fan out over
    them like host operators fan out over ``spine.batches``."""

    delta: Batch
    pre: Tuple[Batch, ...]
    post: Tuple[Batch, ...]


class CNode:
    """Base: a compiled counterpart of one circuit node.

    ``caps`` holds named static capacities; ``init_state`` builds the state
    pytree (or None for stateless nodes); ``eval`` must be pure/traceable.

    ``MONOTONE_CAPS`` names the capacities that integrate the stream (trace
    sizes, per-key gathers against growing groups): their requirements grow
    roughly linearly with tick count, so a warmed-up run can pre-size them
    for a planned run length (compiler.presize) instead of climbing the
    grow/retrace ladder during measurement.
    """

    MONOTONE_CAPS: frozenset = frozenset()

    def __init__(self, node, op):
        self.node = node
        self.op = op
        self.caps: Dict[str, int] = {}

    def profile_meta(self) -> Dict[str, object]:
        """Graph metadata the operator profiler (obs/opprofile.py) joins
        onto this node's attribution row: enough to name the node in a
        report without walking the circuit again."""
        meta: Dict[str, object] = {
            "caps": dict(self.caps),
            "inputs": [int(i) for i in self.node.inputs],
            "sharded": bool(getattr(self, "lead", ())),
        }
        if isinstance(self, _Leveled) and hasattr(self, "level_keys"):
            meta["trace_levels"] = len(self.level_keys)
            slot = getattr(self, "_slot_cap", None)
            if slot:
                meta["slot_cap"] = int(slot)
            # tiered residency tag (dbsp_tpu/residency.py): per-level tier
            # of this trace's state, maintained by the handle's enforcement
            # OUTSIDE the jitted state pytree (tiers are host bookkeeping,
            # never traced data). Absent = fully device-resident.
            tiers = getattr(self, "residency_tiers", None)
            if tiers and any(t != "device" for t in tiers):
                meta["residency_tiers"] = list(tiers)
        return meta

    def init_state(self):
        return None

    def repad_state(self, st):
        """Re-fit a snapshotted state to the CURRENT capacities (after a
        grow); default handles the single-Batch trace states."""
        cap_key = next((k for k in ("trace", "out_trace", "acc_trace")
                        if k in self.caps), None)
        if cap_key and isinstance(st, Batch) and st.cap != self.caps[cap_key]:
            return st.with_cap(self.caps[cap_key])
        return st

    def note_requirement(self, key: str, required: int) -> None:
        """Hook fired with each VALIDATED requirement level — lets a node
        reclassify a capacity once observed behavior contradicts its static
        assumption (see CAggregate's gather)."""

    def eval(self, ctx, state, inputs):  # -> (state', output)
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Stateless nodes
# ---------------------------------------------------------------------------


class CInput(CNode):
    """Source: the tick's feed batch (from the traced generator or the feeds
    argument). The compiler injects the value via ctx.feeds.

    Sharded mode: the host input handle hash-distributes pushed rows
    (io_handles.py sets ``key_sharded`` on sources, mirroring the
    reference's key-hash input routing, input.rs:309-311), so the compiled
    source must uphold the same placement. A traced ``gen_fn`` produces the
    FULL tick batch on every worker (counter-based generation is pure ALU —
    replicating it is far cheaper than exchanging rows over the
    interconnect); each worker then keeps its key-hash share and compacts
    to a per-worker capacity (compaction preserves sort order, so the
    slice stays consolidated)."""

    def eval(self, ctx, state, inputs):
        batch = ctx.feeds.get(self.node.index)
        if batch is None:
            sch = (self.op.key_dtypes, self.op.val_dtypes)
            batch = Batch.empty(*sch)
        lead = getattr(self, "lead", ())
        if not lead:
            return None, batch
        # Sharded: ALWAYS register the requirement (a conditional check
        # would shift the _checks/_req index when a feed appears between
        # retraces and desynchronize validation).
        from jax import lax

        from dbsp_tpu.parallel.exchange import worker_of
        from dbsp_tpu.parallel.mesh import WORKER_AXIS

        workers = lead[0]
        w = lax.axis_index(WORKER_AXIS)
        out = batch.compacted((batch.weights != 0) &
                              (worker_of(batch.keys[0], workers) == w))
        if not self.caps.get("input"):
            # balanced-hash estimate; skew is caught by the requirement
            self.caps["input"] = bucket_cap(max(batch.cap // workers, 8) * 2)
        ctx.require(self, "input", out.live_count())
        return None, out.with_cap(self.caps["input"])


class CPure(CNode):
    """Map/filter/flat_map — the host op's kernel is already a pure
    Batch -> Batch function; reuse it directly. With
    ``defer_consolidate`` (compiler placement pass) a map/flat_map skips
    its trailing consolidation — every consumer canonicalizes anyway."""

    def eval(self, ctx, state, inputs):
        if getattr(self, "defer_consolidate", False):
            return None, self.op._inner_raw(inputs[0])
        return None, self.op._inner(inputs[0])


class CPlus(CNode):
    def eval(self, ctx, state, inputs):
        a, b = inputs
        return None, a.merge_with(b)


class CMinus(CNode):
    def eval(self, ctx, state, inputs):
        return None, inputs[0].merge_with(inputs[1].neg())


class CStreamDistinct(CNode):
    def eval(self, ctx, state, inputs):
        return None, type(self.op)._kernel(inputs[0])


class CNeg(CNode):
    def eval(self, ctx, state, inputs):
        return None, inputs[0].neg()


class CSumN(CNode):
    def eval(self, ctx, state, inputs):
        cat = concat_batches(list(inputs))
        if getattr(self, "defer_consolidate", False):
            return None, cat
        return None, cat.consolidate()


class COutput(CNode):
    """Sink: expose the batch as a per-tick run output."""

    def eval(self, ctx, state, inputs):
        ctx.outputs[self.node.index] = inputs[0]
        return None, None


# ---------------------------------------------------------------------------
# Stateful nodes
# ---------------------------------------------------------------------------


def _migrate_spine(spine) -> Optional[Batch]:
    """One consolidated batch of a host-path spine (None if empty) — the
    state-migration bridge for warm starts; consolidates ONCE."""
    if not spine.batches:
        return None
    return spine.consolidated()


class CTrace(CNode, _Leveled):
    """integrate_trace as a leveled static trace (see module doc)."""

    MONOTONE_CAPS = frozenset({"trace"})
    TAIL_KEY = "trace"
    DEFAULT_CAP = 1024

    def __init__(self, node, op):
        super().__init__(node, op)
        self._migrated = _migrate_spine(op.spine)
        live = 0 if self._migrated is None \
            else int(self._migrated.max_worker_live())
        self.caps["trace"] = bucket_cap(max(live * 2, self.DEFAULT_CAP))
        self._init_level_caps()

    def init_state(self):
        sch = (self.op.key_dtypes, self.op.val_dtypes)
        return self._levels_init(sch, getattr(self, "lead", ()),
                                 self._migrated)

    def repad_state(self, st):
        return self._levels_repad(st)

    def eval(self, ctx, state, inputs):
        delta = inputs[0]
        post = self._levels_append(ctx, state, delta)
        pre = self._view_levels(state[0])
        # LAZY post view (see lazy_post_enabled): after a slotted append
        # the post-tick trace IS pre + delta — hand consumers the delta as
        # one more ladder level instead of making them read the slot just
        # written. Gated on a tagged-consolidated delta (the slot ladder's
        # run invariant) — anything else keeps the materialized view.
        if getattr(self, "_append_slotted", False) and \
                delta.sorted_runs == 1 and lazy_post_enabled():
            post_view: Tuple[Batch, ...] = (*pre, delta)
        else:
            post_view = self._view_levels(post[0])
        return post, CView(delta=delta, pre=pre, post=post_view)


class CJoin(CNode):
    """Bilinear incremental join over CViews (operators/join.py semantics:
    ΔA ⋈ trace(B)_post  +  ΔB ⋈ trace(A)_pre), one consolidation."""

    def __init__(self, node, op):
        super().__init__(node, op)
        self.caps["left"] = 0    # sized on first trace from delta caps
        self.caps["right"] = 0

    def eval(self, ctx, state, inputs):
        left, right = inputs
        lcore = self.op._left_core
        rcore = self.op._right_core
        nk = lcore.nk
        cap_l = ensure_side_cap(self, "left", left.delta.cap)
        cap_r = ensure_side_cap(self, "right", right.delta.cap)
        # ΔL joins every level of trace(R) post-append; ΔR every level of
        # trace(L) pre-append — each side's K level results land in ONE
        # shared buffer (requirement = total across levels). With a
        # permutation pair fn on the native path each side comes back as
        # one consolidated run (sorted_emit), so the final consolidate is
        # a 2-run rank fold — one linear merge, NO sort; otherwise it
        # sorts 2 buffers regardless of K.
        lout, ltot = join_levels(left.delta, right.post, nk, lcore.fn,
                                 cap_l,
                                 sorted_emit=lcore.sorted_emit(
                                     left.delta, right.post))
        ctx.require(self, "left", ltot)
        rout, rtot = join_levels(right.delta, left.pre, nk, rcore.fn,
                                 cap_r,
                                 sorted_emit=rcore.sorted_emit(
                                     right.delta, left.pre))
        ctx.require(self, "right", rtot)
        out = concat_batches([lout, rout])
        if not getattr(self, "defer_consolidate", False):
            out = out.consolidate()
        return None, out


class CAggregate(CNode):
    """General incremental aggregate (Min/Max/Fold): gather touched groups
    from the input trace view, reduce, diff against own output trace.

    Semigroup aggregates (``agg.insert_combinable`` — Min/Max) take a fast
    path: groups whose delta holds only insertions combine the delta's own
    reduction with the previous output (new max = max(old max, delta max)),
    so NO history comes back from the input trace — per-tick cost is
    O(delta), not O(touched history). The combine is only sound while every
    net weight in the integrated trace is non-negative (a positive delta
    row could otherwise partially cancel an over-retracted trace row and
    surface a value that is NOT present); the state carries an
    ``ever_negative`` flag — once ANY retraction has entered the stream,
    touched groups re-gather (requirement-checked; stays zero on
    append-only streams like Nexmark bids). The reference's eval
    (aggregate/mod.rs:600) always walks the touched groups' trace cursors —
    this is a strict improvement enabled by keeping the previous outputs in
    a probe-able batch."""

    # gather grows too: touched groups' FULL histories come back from the
    # input trace, and hot groups accumulate rows over the run
    MONOTONE_CAPS = frozenset({"out_trace", "gather"})

    def __init__(self, node, op):
        super().__init__(node, op)
        self.caps["gather"] = 0
        self.caps["out_trace"] = 0
        if getattr(op.agg, "insert_combinable", False):
            # the gather only serves retracted groups -> not monotone...
            self.MONOTONE_CAPS = frozenset({"out_trace"})

    def note_requirement(self, key, required):
        # ...until a retraction actually engages the slow path: from then on
        # every touched group re-gathers its FULL history, so the gather
        # requirement does grow with the run — reclassify it as monotone so
        # presize projects it linearly instead of climbing a grow/retrace
        # ladder (each retrace ~minutes over a tunneled accelerator)
        if key == "gather" and required > 0 \
                and "gather" not in self.MONOTONE_CAPS:
            self.MONOTONE_CAPS = self.MONOTONE_CAPS | {"gather"}

    def init_state(self):
        # ever_neg carries the same per-worker lead axis as the batch state:
        # every state leaf must be rank>=1 under PartitionSpec('workers') and
        # the shard_map squeeze (a[0]) assumes a leading worker axis
        lead = getattr(self, "lead", ())
        migrated = _migrate_spine(self.op.out_spine)
        if not self.caps["out_trace"]:
            live = 0 if migrated is None else int(migrated.max_worker_live())
            self.caps["out_trace"] = bucket_cap(max(live * 2, 1024))
        if migrated is not None:
            # a host-warmed spine has unknown retraction history — the fast
            # path must assume the worst
            return (migrated.with_cap(self.caps["out_trace"]),
                    jnp.full(lead, True))
        return (Batch.empty(*self.op.out_schema, cap=self.caps["out_trace"],
                            lead=lead),
                jnp.full(lead, False))

    def repad_state(self, st):
        batch, ever_neg = st
        if batch.cap != self.caps["out_trace"]:
            batch = batch.with_cap(self.caps["out_trace"])
        return (batch, ever_neg)

    def eval(self, ctx, state, inputs):
        from dbsp_tpu.operators.aggregate import _diff_outputs_impl
        from dbsp_tpu.zset import cursor

        view: CView = inputs[0]
        out_trace, ever_neg = state
        agg = self.op.agg
        nk = len(self.op.key_dtypes)
        delta = view.delta
        if not self.caps.get("queries"):
            self.caps["queries"] = 64  # trim_queries' seed, same contract
        # effective query capacity = the trim_queries slice semantics: the
        # unique-key buffer can never hold more rows than the delta has
        q_cap = min(self.caps["queries"], delta.cap)
        fast = getattr(agg, "insert_combinable", False)
        if not self.caps["gather"]:
            self.caps["gather"] = 64 if fast else max(64, 2 * q_cap)

        ever_neg = ever_neg | jnp.any(delta.weights < 0)
        # the ladder gate rides as a RUNTIME value: on the fast path the
        # slow re-gather engages only once ANY retraction has entered the
        # stream (a positive delta may then partially cancel a net-negative
        # trace row — combine would be unsound); no retrace when it flips
        flag = ever_neg if fast else jnp.asarray(True)
        # ONE fused call: unique touched keys (run-boundary scan of the
        # consolidated delta — the same scan feeds the fast path's segment
        # ids, never recomputed), previous outputs from the out trace
        # (exact q_cap expansion: it holds one live row per present key),
        # the touched groups' ladder histories netted + reduced, and the
        # fast path's delta-side reduction (cursor.agg_ladder — native
        # megakernel / Pallas / stitched XLA control)
        (qkeys, qlive, nq, old_vals, old_present, lad_vals, lad_present,
         d_vals, d_present, gtot) = cursor.agg_ladder(
            delta, nk, out_trace, view.post, agg, q_cap,
            self.caps["gather"], fast, flag)
        ctx.require(self, "queries", nq)
        ctx.require(self, "gather", gtot)
        if fast:
            fast_vals = agg.combine(old_vals, old_present, d_vals,
                                    d_present)
            fast_present = old_present | d_present
            slow = qlive & jnp.broadcast_to(ever_neg, qlive.shape)
            new_vals = tuple(jnp.where(slow, sv.astype(fv.dtype), fv)
                             for sv, fv in zip(lad_vals, fast_vals))
            new_present = jnp.where(slow, lad_present, fast_present)
        else:
            new_vals, new_present = lad_vals, lad_present

        cols, w = _diff_outputs_impl(qkeys, qlive, new_vals, new_present,
                                     old_vals, old_present)
        out = Batch(cols[:nk], cols[nk:], w, runs=(int(w.shape[-1]),))
        state2, required = static_append(out_trace, out)
        ctx.require(self, "out_trace", required)
        return (state2, ever_neg), out


class CLinearAggregate(CNode):
    """Linear fast path: per-key accumulator state in a static trace batch
    (one live row per key — NOT leveled, see module doc)."""

    MONOTONE_CAPS = frozenset({"acc_trace"})

    def __init__(self, node, op):
        super().__init__(node, op)
        self.caps["acc_trace"] = 0

    def init_state(self):
        migrated = _migrate_spine(self.op.acc_spine)
        if not self.caps["acc_trace"]:
            live = 0 if migrated is None else int(migrated.max_worker_live())
            self.caps["acc_trace"] = bucket_cap(max(live * 2, 1024))
        if migrated is not None:
            return migrated.with_cap(self.caps["acc_trace"])
        return Batch.empty(*self.op._state_schema,
                           cap=self.caps["acc_trace"],
                           lead=getattr(self, "lead", ()))

    def eval(self, ctx, state, inputs):
        from dbsp_tpu.operators.aggregate import (_gather_level_impl,
                                                  _unique_keys_impl)
        from dbsp_tpu.operators.aggregate_linear import (_combine_diff_impl,
                                                         _net_state_impl,
                                                         _weigh_deltas_impl)

        agg = self.op.agg
        nk = len(self.op.key_dtypes)
        delta = inputs[0]
        qkeys, qlive = _unique_keys_impl(delta, nk)
        qkeys, qlive = trim_queries(ctx, self, qkeys, qlive)
        q_cap = qlive.shape[-1]
        acc_delta, cnt_delta = _weigh_deltas_impl(delta, agg, nk)
        # per-unique-key segment sums, packed like qkeys: trim to match
        # (ids past q_cap are caught by the "queries" requirement)
        acc_delta = tuple(a[..., :q_cap] for a in acc_delta)
        cnt_delta = cnt_delta[..., :q_cap]

        # the consolidated accumulator trace holds one live row per key, so
        # a q_cap expansion is exact — no requirement check needed
        qrow, vals, w, _ = _gather_level_impl(qkeys, qlive, state, q_cap)
        old = _net_state_impl(((qrow, vals, w),), q_cap)
        out, sdiff = _combine_diff_impl(qkeys, qlive, tuple(acc_delta),
                                        cnt_delta, *old, agg, nk)
        state2, required = static_append(state, sdiff)
        ctx.require(self, "acc_trace", required)
        return state2, out


class CTopK(CNode):
    """Incremental per-key top-K (operators/topk.py): recompute touched
    groups' top-K from the input trace view, diff against the previous
    output kept in a static out trace (k live rows per key — NOT leveled,
    see module doc; the old gather is exact at k*q_cap)."""

    MONOTONE_CAPS = frozenset({"out_trace", "gather"})

    def __init__(self, node, op):
        super().__init__(node, op)
        self.caps["gather"] = 0
        self.caps["out_trace"] = 0

    def init_state(self):
        migrated = _migrate_spine(self.op.out_spine)
        if not self.caps["out_trace"]:
            live = 0 if migrated is None else int(migrated.max_worker_live())
            self.caps["out_trace"] = bucket_cap(max(live * 2, 1024))
        if migrated is not None:
            return migrated.with_cap(self.caps["out_trace"])
        return Batch.empty(*self.op.schema, cap=self.caps["out_trace"],
                           lead=getattr(self, "lead", ()))

    def eval(self, ctx, state, inputs):
        from dbsp_tpu.operators.aggregate import (_gather_level_impl,
                                                  _unique_keys_impl)
        from dbsp_tpu.operators.topk import _topk_rows

        view: CView = inputs[0]
        nk = len(self.op.schema[0])
        delta = view.delta
        qkeys, qlive = _unique_keys_impl(delta, nk)
        qkeys, qlive = trim_queries(ctx, self, qkeys, qlive)
        q_cap = qlive.shape[-1]
        if not self.caps["gather"]:
            self.caps["gather"] = max(64, 2 * q_cap)

        g, gtot = gather_levels(qkeys, qlive, view.post, self.caps["gather"])
        ctx.require(self, "gather", gtot)
        new_part = _topk_rows(g[0], qkeys, g[1], g[2], self.op.k,
                              self.op.largest, 1, q_cap)
        # the consolidated out trace holds <= k live rows per key: exact cap
        o = _gather_level_impl(qkeys, qlive, state, self.op.k * q_cap)[:3]
        old_part = _topk_rows(o[0], qkeys, o[1], o[2], self.op.k,
                              self.op.largest, -1, q_cap)
        out = concat_batches([new_part, old_part]).consolidate()
        state2, required = static_append(state, out)
        ctx.require(self, "out_trace", required)
        return state2, out


class CDistinct(CNode):
    """Incremental distinct over a CView (stateless given the view); the
    old-weight lookup probes every pre-tick level in one fused cursor."""

    def eval(self, ctx, state, inputs):
        from dbsp_tpu.operators.distinct import _distinct_delta_impl
        from dbsp_tpu.zset import cursor

        view: CView = inputs[0]
        old_w = cursor.old_weights_ladder(view.delta, view.pre)
        return None, _distinct_delta_impl(view.delta, old_w)


def range_gather_levels(qp, qlo, qhi, qlive, levels: Sequence[Batch],
                        out_cap: int):
    """Per-row [lo, hi] time-range gather over K trace levels in ONE fused
    cursor launch — the range twin of :func:`gather_levels` through the
    SAME shared entry point (cursor.gather_ladder with distinct lo/hi
    probe columns + the time key column gathered back; shared with
    timeseries/rolling.py's host RangeGather). Returns
    ((qrow, t, vals, w), unclamped total); dead slots carry qrow == q_cap
    (the trash segment) + sentinel cols."""
    from dbsp_tpu.zset import cursor

    assert levels
    (qrow, cols, w), total = cursor.gather_ladder(
        (qp, qlo), qlive, tuple(levels), out_cap, qhi_keys=(qp, qhi),
        gather_keys=1)
    return (qrow, cols[0], cols[1:], w), total.astype(jnp.int64)


class CRangeJoin(CNode):
    """Incremental relative-range join over CViews (operators/join_range.py
    semantics: ΔL ⋈r trace(R)_post + ΔR ⋈r trace(L)_pre), with each side's
    K per-level expansions landing in one shared static buffer."""

    def __init__(self, node, op):
        super().__init__(node, op)
        self.caps["left"] = 0
        self.caps["right"] = 0

    def _fan(self, ctx, cap_key, delta, levels, core):
        from dbsp_tpu.operators.join_range import _range_join_level_impl

        out_cap = self.caps[cap_key]
        j = jnp.arange(out_cap, dtype=jnp.int32)
        bufs = wbuf = None
        offset = jnp.asarray(0, jnp.int32)
        req = jnp.asarray(0, jnp.int64)
        for lvl in levels:
            out, total = _range_join_level_impl(
                delta, lvl, core.lo_off, core.hi_off, core.fn, out_cap)
            req = req + total.astype(jnp.int64)
            t32 = jnp.minimum(total, out_cap).astype(jnp.int32)
            idx = jnp.where(j < t32, j + offset, out_cap)
            if bufs is None:
                bufs = tuple(kernels.sentinel_fill((out_cap,), c.dtype)
                             for c in out.cols)
                wbuf = jnp.zeros((out_cap,), out.weights.dtype)
            bufs = tuple(b.at[idx].set(c, mode="drop")
                         for b, c in zip(bufs, out.cols))
            wbuf = wbuf.at[idx].set(jnp.where(j < t32, out.weights, 0),
                                    mode="drop")
            offset = jnp.minimum(offset + t32, out_cap)
        ctx.require(self, cap_key, req)
        nko = len(self.op.out_schema[0])
        return Batch(bufs[:nko], bufs[nko:], wbuf)

    def eval(self, ctx, state, inputs):
        left, right = inputs
        ensure_side_cap(self, "left", left.delta.cap)
        ensure_side_cap(self, "right", right.delta.cap)
        lout = self._fan(ctx, "left", left.delta, right.post,
                         self.op._left)
        rout = self._fan(ctx, "right", right.delta, left.pre,
                         self.op._right)
        out = concat_batches([lout, rout])
        if not getattr(self, "defer_consolidate", False):
            out = out.consolidate()
        return None, out


class CRolling(CNode):
    """Partitioned rolling aggregate (timeseries/rolling.py) over a CView:
    find dirty (p, t') slots, recompute each window [t'-range, t'] from the
    input trace levels, diff against the previous outputs kept in a static
    out trace. The window-recompute path only (the radix-tree fast path
    keeps host-driven level state; rolling queries wanting it run the host
    scheduler) — within one tick everything is the same shared-buffer fan
    machinery as the equality aggregates."""

    MONOTONE_CAPS = frozenset({"out_trace", "affected", "window"})

    def __init__(self, node, op):
        super().__init__(node, op)
        self.caps["affected"] = 0
        self.caps["dirty"] = 0
        self.caps["window"] = 0
        self.caps["out_trace"] = 0

    def init_state(self):
        migrated = _migrate_spine(self.op.out_spine)
        if not self.caps["out_trace"]:
            live = 0 if migrated is None else int(migrated.max_worker_live())
            self.caps["out_trace"] = bucket_cap(max(live * 2, 1024))
        if migrated is not None:
            return migrated.with_cap(self.caps["out_trace"])
        return Batch.empty(*self.op.out_schema,
                           cap=self.caps["out_trace"],
                           lead=getattr(self, "lead", ()))

    def eval(self, ctx, state, inputs):
        from dbsp_tpu.operators.aggregate import (_TupleMax,
                                                  _diff_outputs_impl,
                                                  _gather_level_impl,
                                                  _reduce_groups_impl)
        from dbsp_tpu.timeseries.rolling import (_dirty_rows_impl,
                                                 _rolling_reduce_impl)

        view: CView = inputs[0]
        delta = view.delta
        rng = self.op.range_ms
        dp, dt = delta.keys[0], delta.keys[1]
        dlive = delta.weights != 0
        if not self.caps["affected"]:
            self.caps["affected"] = max(64, 2 * delta.cap)
            self.caps["dirty"] = max(64, 2 * delta.cap)
            self.caps["window"] = max(64, 4 * delta.cap)

        # 1. dirty slots: trace rows in [ts, ts+range] per delta row (keys
        # only) + the delta's own rows
        key_only = [Batch(b.keys, (), b.weights) for b in view.post]
        (qrow, t, _v, w), aff_req = range_gather_levels(
            dp, dt, dt + rng, dlive, key_only, self.caps["affected"])
        ctx.require(self, "affected", aff_req)
        ap, at, alive = _dirty_rows_impl(dp, dt, dlive, qrow, t, w)
        ctx.require(self, "dirty", jnp.sum(alive))
        a_cap = self.caps["dirty"]

        def fit(arr, fill):
            n = arr.shape[-1]
            if n >= a_cap:
                return arr[..., :a_cap]
            pad = jnp.full((*arr.shape[:-1], a_cap - n), fill, arr.dtype)
            return jnp.concatenate([arr, pad], axis=-1)

        ap = fit(ap, kernels.sentinel_for(ap.dtype))
        at = fit(at, kernels.sentinel_for(at.dtype))
        alive = fit(alive, False)

        # 2. recompute each dirty window from the input trace
        (wrow, wt, wvals, ww), win_req = range_gather_levels(
            ap, at - rng, at, alive, view.post, self.caps["window"])
        ctx.require(self, "window", win_req)
        new_vals, new_present = _rolling_reduce_impl(
            wrow, wt, wvals, ww, at, self.op.agg, a_cap)

        # 3. diff vs previous outputs (one live row per (p, t'): exact)
        oqrow, ovals, ow, _ = _gather_level_impl((ap, at), alive, state,
                                                 a_cap)
        old_vals, old_present = _reduce_groups_impl(
            ((oqrow, ovals, ow),), _TupleMax(len(self.op.agg.out_dtypes)),
            a_cap)
        cols, w = _diff_outputs_impl((ap, at), alive, new_vals, new_present,
                                     old_vals, old_present)
        out = Batch(cols[:2], cols[2:], w, runs=(int(w.shape[-1]),))
        state2, required = static_append(state, out)
        ctx.require(self, "out_trace", required)
        return state2, out


class CUpsertIn(CNode):
    """Upsert source (operators/upsert.py): the host feeds a COMMAND batch
    (unique sorted keys; +1 rows carry new values, -1 rows are deletes);
    the node diffs it against the maintained map state to emit exact
    Z-set deltas — retract the touched keys' live rows, insert the new
    values (upsert.rs:37's state diff, with the state as a static batch)."""

    MONOTONE_CAPS = frozenset({"state"})

    def __init__(self, node, op):
        super().__init__(node, op)
        migrated = _migrate_spine(op.spine)
        live = 0 if migrated is None else int(migrated.max_worker_live())
        self.caps["state"] = bucket_cap(max(live * 2, 1024))
        self._migrated = migrated

    def init_state(self):
        if self._migrated is not None:
            return self._migrated.with_cap(self.caps["state"])
        return Batch.empty(self.op.key_dtypes, self.op.val_dtypes,
                           cap=self.caps["state"],
                           lead=getattr(self, "lead", ()))

    def eval(self, ctx, state, inputs):
        from dbsp_tpu.operators.aggregate import _gather_level_impl
        from dbsp_tpu.operators.upsert import _retractions

        cmds = ctx.feeds.get(self.node.index)
        if cmds is None:
            cmds = Batch.empty(self.op.key_dtypes, self.op.val_dtypes)
        nk = len(self.op.key_dtypes)
        qkeys = cmds.keys[:nk]
        qlive = cmds.weights != 0
        q_cap = qlive.shape[-1]
        qrow, vals, w, _ = _gather_level_impl(qkeys, qlive, state, q_cap)
        retract = _retractions(qrow, qkeys, vals, w)
        inserts = cmds.masked(cmds.weights > 0)
        out = concat_batches([retract, inserts]).consolidate()
        state2, required = static_append(state, out)
        ctx.require(self, "state", required)
        return state2, out


class CZ1Input(CNode):
    """Input half of a strict z^-1 feedback (operators/z1.py; the node pair
    builder.py:85-116 schedules as source + sink). Owns the delayed value
    as a static-capacity batch: the arriving value (e.g. integrate's
    ``acc = s + z1(acc)``) has a per-tick merge capacity, so it re-buckets
    to the state cap with a requirement check — the host path's
    ``shrink_to_fit`` sync, turned into the standard grow/replay contract."""

    MONOTONE_CAPS = frozenset({"trace"})

    def __init__(self, node, op):
        super().__init__(node, op)
        migrated = op.state if isinstance(op.state, Batch) else None
        live = 0 if migrated is None else int(migrated.max_worker_live())
        self.caps["trace"] = bucket_cap(max(live * 2, 1024))
        self._migrated = migrated

    def init_state(self):
        lead = getattr(self, "lead", ())
        if self._migrated is not None and \
                int(self._migrated.max_worker_live()) > 0:
            return self._migrated.with_cap(self.caps["trace"])
        zero = self.op.zero_factory()
        assert isinstance(zero, Batch), (
            "compiled z^-1 supports Batch-valued streams only")
        return Batch.empty(zero.key_dtypes(), zero.val_dtypes(),
                           cap=self.caps["trace"], lead=lead,
                           weight_dtype=zero.weights.dtype)

    def eval(self, ctx, state, inputs):
        v = inputs[0]
        merged = v if v.cap == self.caps["trace"] else \
            v.with_cap(self.caps["trace"])
        ctx.require(self, "trace", v.live_count())
        return merged, None


class CZ1Output(CNode):
    """Output half: emits the value its partner stored LAST tick (state
    flows through the states dict under the partner's index — ``ctx.states``
    is the tick's INPUT state, so this is exactly out(t) = in(t-1))."""

    def eval(self, ctx, state, inputs):
        st = ctx.states.get(str(self.node.partner))
        assert st is not None, "z1 feedback loop was never closed"
        return None, st


# ---------------------------------------------------------------------------
# Time-series nodes (watermark / apply / window)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CMaybe:
    """A device scalar stream value that may not exist yet (the host path's
    ``None`` before the first event — e.g. a watermark). ``value`` is
    arbitrary traced arithmetic; ``valid`` masks every consumer, so the
    garbage value computed before the first event never becomes observable."""

    valid: jnp.ndarray
    value: object


_WM_FLOOR = int(jnp.iinfo(jnp.int64).min) // 4  # headroom for bound arithmetic


def truncate_below(batch: Batch, bound) -> Batch:
    """Drop rows whose leading key is below ``bound`` (compiled analog of
    ``Spine.truncate_keys_below`` — the TraceBound GC, operator/trace.rs:29);
    capacity unchanged, live rows stay packed + sorted. The comparison runs
    in int64: the pre-first-bounds sentinel (_WM_FLOOR) would wrap if cast
    down to an int32 key column and truncate live negative-key rows."""
    k0 = batch.keys[0]
    return batch.compacted(
        (batch.weights != 0) &
        (k0.astype(jnp.int64) >= jnp.asarray(bound, jnp.int64)))


class CWatermark(CNode):
    """``watermark_monotonic`` (watermark.rs:33): running max of a live
    timestamp column minus lateness, as device scalars — state is
    (wm, valid) instead of the host path's ``None``-able Python int.

    Sharded: the watermark is a GLOBAL property of the stream — each
    worker's local max combines across the mesh with one ``lax.pmax``
    (the reference computes it on the unsharded stream; a collective is
    the SPMD equivalent), so every worker carries the same (wm, valid)
    and downstream window bounds agree everywhere."""

    def init_state(self):
        lead = getattr(self, "lead", ())
        return (jnp.full(lead, _WM_FLOOR, jnp.int64),
                jnp.full(lead, False))

    def eval(self, ctx, state, inputs):
        batch = inputs[0]
        ts = self.op.ts_fn(batch.keys, batch.vals).astype(jnp.int64)
        live = batch.weights != 0
        m = jnp.max(jnp.where(live, ts, _WM_FLOOR))
        any_live = jnp.any(live)
        if getattr(self, "lead", ()):
            from jax import lax

            from dbsp_tpu.parallel.mesh import WORKER_AXIS

            m = lax.pmax(m, WORKER_AXIS)
            any_live = lax.pmax(any_live.astype(jnp.int32),
                                WORKER_AXIS) > 0
        wm0, valid0 = state
        wm1 = jnp.where(any_live,
                        jnp.maximum(wm0, m - self.op.lateness), wm0)
        valid1 = valid0 | any_live
        return (wm1, valid1), CMaybe(valid1, wm1)


class CApply(CNode):
    """Host ``apply`` over scalar streams: trace the Python fn on the device
    value. A ``CMaybe`` input keeps its validity (the fn's host-side
    ``None`` branch is unreachable under tracing — tracers are never None)."""

    def eval(self, ctx, state, inputs):
        v = inputs[0]
        if isinstance(v, CMaybe):
            return None, CMaybe(v.valid, self.op.fn(v.value))
        return None, self.op.fn(v)


class CWindow(CNode):
    """Moving-bounds window (window.rs:75-130) over a compiled trace view.

    Same three-part delta as the host op (new rows in [a1,b1); minus rows
    that slid out of [a0,min(a1,b0)); plus rows that slid in from
    [max(b0,a1),b1)) — but range extraction is two masked slices of the
    SINGLE consolidated trace batch instead of per-spine-level cursors, and
    the pre-first-bounds tick is expressed by masking (weights to 0) rather
    than an early return. With ``gc=True`` the lower bound feeds back into
    the trace node's state via ``ctx.gc_bounds`` — the compiler truncates
    the trace inside the same XLA program (TraceBound GC)."""

    def __init__(self, node, op):
        super().__init__(node, op)
        self.caps["slide_out"] = 0
        self.caps["slide_in"] = 0

    def init_state(self):
        # (a0, b0, had_bounds) — per worker under a mesh (the bounds stream
        # is globally consistent, see CWatermark, so the slices agree; each
        # worker windows its own key-hash slice and the union is exact)
        lead = getattr(self, "lead", ())
        return (jnp.full(lead, 0, jnp.int64), jnp.full(lead, 0, jnp.int64),
                jnp.full(lead, False))

    def eval(self, ctx, state, inputs):
        from dbsp_tpu.timeseries.window import _filter_window, _slice_range

        view, bounds = inputs
        if not isinstance(bounds, CMaybe):
            bounds = CMaybe(jnp.asarray(True), bounds)
        a1, b1 = (jnp.asarray(x, jnp.int64) for x in bounds.value)
        valid1 = bounds.valid
        a0, b0, had = state
        # first bounds ever -> previous window is the empty range [a1, a1)
        a0e = jnp.where(had, a0, a1)
        b0e = jnp.where(had, b0, a1)

        if not self.caps["slide_out"]:
            cap = max(64, view.delta.cap)
            self.caps["slide_out"] = cap
            self.caps["slide_in"] = cap
        # slide ranges are extracted per trace level (shared slide caps —
        # the requirement's running max sizes them to the worst level)
        parts = [_filter_window(view.delta, a1, b1)]
        for lvl in view.pre:
            out_b, n_out = _slice_range(lvl, a0e, jnp.minimum(a1, b0e),
                                        self.caps["slide_out"])
            ctx.require(self, "slide_out", n_out)
            parts.append(out_b.neg())
            in_b, n_in = _slice_range(lvl, jnp.maximum(b0e, a1), b1,
                                      self.caps["slide_in"])
            ctx.require(self, "slide_in", n_in)
            parts.append(in_b)
        # masked: everything is dead until bounds exist
        out = concat_batches(parts).consolidate().masked(valid1)

        if self.op.gc:
            ctx.gc_bounds[self.node.inputs[0]] = \
                jnp.where(valid1, a1, jnp.asarray(_WM_FLOOR, jnp.int64))
        state2 = (jnp.where(valid1, a1, a0), jnp.where(valid1, b1, b0),
                  had | valid1)
        return state2, out


# ---------------------------------------------------------------------------
# Communication nodes (sharded compiled step only; the whole step runs under
# one shard_map, so these are plain collective calls)
# ---------------------------------------------------------------------------


class CExchange(CNode):
    """Key-hash repartition (shard.rs:89): bucket + all_to_all + compact to
    a static per-worker capacity. The all_to_all's raw output capacity is
    W x cap_local (worst-case skew); the compiled path re-buckets to
    ``caps['exchange']`` with a requirement check instead of the host path's
    per-eval scalar sync. Rows past the static bucket would fall off the
    ``with_cap`` slice — the requirement check turns that into an overflow
    REPLAY (grow + re-run the interval), counted on
    ``dbsp_tpu_exchange_overflow_total`` under kind=exchange, never
    silent data loss."""

    # worst-worker live rows at the last validation — the observable the
    # skew gauges export (occupancy ratio = last_required / cap)
    last_required: int = 0

    def note_requirement(self, key: str, required: int) -> None:
        if key == "exchange":
            self.last_required = required

    def eval(self, ctx, state, inputs):
        from dbsp_tpu.parallel.exchange import exchange_local

        batch = inputs[0]
        out = exchange_local(batch, self.op.nworkers)
        if not self.caps.get("exchange"):
            self.caps["exchange"] = batch.cap  # balanced-hash estimate
        ctx.require(self, "exchange", out.live_count())
        return None, out.with_cap(self.caps["exchange"])


class CUnshard(CNode):
    """All-to-one gather (gather.rs:41): the union lands on worker 0; every
    other worker holds an empty (dead-sentinel) slice. Keeping exactly ONE
    live copy preserves Z-set weights through whatever follows — a
    re-exchange re-distributes rows (not W copies of them) and an output
    union counts each row once. Output capacity is exact (sum of per-worker
    caps), so no requirement check is needed."""

    def eval(self, ctx, state, inputs):
        from jax import lax

        from dbsp_tpu.parallel.exchange import gather_local
        from dbsp_tpu.parallel.mesh import WORKER_AXIS

        union = gather_local(inputs[0])
        return None, union.masked(lax.axis_index(WORKER_AXIS) == 0)


# ---------------------------------------------------------------------------
# pytree registration for the inter-node value types
# ---------------------------------------------------------------------------
# Inside the FUSED step program CView/CMaybe only ever live within one
# trace, so they never needed to be pytrees. The segmented profiler
# (obs/opprofile.py) compiles each node's eval as its OWN jit program, so
# these values cross jit boundaries there — registering them makes that
# legal without changing anything on the fused path (no tree_map in
# compiler.py ever receives one: states, feeds, and outputs carry only
# Batches and arrays).

jax.tree_util.register_pytree_node(
    CView,
    lambda v: ((v.delta, v.pre, v.post), None),
    lambda _, c: CView(*c))
jax.tree_util.register_pytree_node(
    CMaybe,
    lambda v: ((v.valid, v.value), None),
    lambda _, c: CMaybe(*c))
