"""Concurrency schema: declared guards for every shared mutable field of
the serving plane.

Sibling of :data:`dbsp_tpu.checkpoint.STATE_SCHEMA` — where that registry
claims each serving-state field's *persistence* disposition so restore can
never silently drop state, this one claims each field's *guard* so a data
race can never silently appear. Both registries are linted in BOTH
directions (unclaimed field / stale claim) through the shared walker in
``tools/schema_walk.py``; the consumers are:

* ``tools/check_concurrency.py`` — the STATIC pass: verifies lock-guarded
  fields are only touched under their declared lock (rule C001), builds
  the static lock-order graph from nested ``with`` acquisitions and
  reports cycles (C002), and flags cross-class private-lock reach-through
  (C003);
* ``dbsp_tpu.testing.tsan`` — the RUNTIME sanitizer (``DBSP_TPU_TSAN=1``):
  instruments locks and attribute access on the classes registered here,
  enforcing the declared guards plus Eraser-style lockset inference
  (Savage et al., TOCS'97) and lock-order inversion detection.

Guard taxonomy (the value strings in :data:`CONCURRENCY_SCHEMA`):

``immutable``
    Bound once during construction (``__init__`` or a class-level
    default) and never rebound. Method calls on the object are fine —
    ``threading.Event``/``queue.Queue`` fields are ``immutable`` bindings
    of internally-synchronized objects.
``lock(<attr>)``
    Every read AND write must hold ``self.<attr>``: inside a
    ``with self.<attr>:`` block, or in a method whose signature line
    carries a ``# holds: <attr>`` marker (callers own the acquisition —
    the ``*_locked`` idiom). The strictest claim; use it when lock-free
    reads would observe torn multi-field state.
``writelock(<attr>)``
    Writes (assignment, augmented assignment, subscript stores, mutating
    container calls) must hold ``self.<attr>``; bare reads are allowed by
    declared invariant — single GIL-atomic loads of a monotone or latched
    value (the pervasive locked-writes/lock-free-stats idiom).
``owner``
    Thread-confined: after construction exactly one thread touches the
    field. Statically exempt; the runtime sanitizer records the first
    accessing thread and flags any second thread.
``lockset``
    Externally synchronized — the protecting lock belongs to another
    object (e.g. fields only mutated on paths serialized by the owning
    controller's step lock). Statically exempt; the runtime sanitizer
    runs pure Eraser inference over WRITES: once a second thread writes,
    the intersection of lock sets held across all writes must stay
    non-empty.
``gil-atomic: <why>``
    Exempt by declared invariant; the rationale is REQUIRED and the lint
    rejects a bare ``gil-atomic``. For single reference assignments whose
    races are benign by design (last-write-wins caches, wiring that
    happens strictly before the threads exist).

Every guard may carry a trailing ``: <note>``; for ``gil-atomic`` the
note is the load-bearing invariant. Static findings are waivable with a
``# concurrency: ok`` comment on the flagged line; runtime findings are
not waivable — fix the race or change the claim.
"""

from __future__ import annotations

import re
from typing import Dict, NamedTuple, Optional, Tuple

#: marker comment on a ``def`` line documenting that callers invoke this
#: method with the named lock(s) held (comma-separated attr names)
HOLDS_MARKER = "# holds:"

#: waiver comment suppressing a static finding on its line
WAIVER = "# concurrency: ok"

#: (file relative to repo root, class name) pairs under schema control —
#: the serving plane's shared-state surface. tools/check_concurrency.py
#: walks these; dbsp_tpu.testing.tsan instruments their instances.
CONCURRENCY_CLASSES: Tuple[Tuple[str, str], ...] = (
    ("dbsp_tpu/io/controller.py", "Controller"),
    ("dbsp_tpu/io/controller.py", "_InputEndpoint"),
    ("dbsp_tpu/io/controller.py", "_OutputEndpoint"),
    ("dbsp_tpu/io/server.py", "CircuitServer"),
    ("dbsp_tpu/manager.py", "PipelineManager"),
    ("dbsp_tpu/manager.py", "Pipeline"),
    ("dbsp_tpu/manager.py", "_CompilerService"),
    ("dbsp_tpu/io/transport.py", "FileInputTransport"),
    ("dbsp_tpu/io/transport.py", "FileOutputTransport"),
    ("dbsp_tpu/io/transport.py", "KafkaInputTransport"),
    ("dbsp_tpu/io/transport.py", "KafkaOutputTransport"),
    ("dbsp_tpu/io/minikafka.py", "MiniKafkaBroker"),
    ("dbsp_tpu/io/minikafka.py", "_Conn"),
    ("dbsp_tpu/io/minikafka.py", "MiniConsumer"),
    ("dbsp_tpu/io/minikafka.py", "MiniProducer"),
    ("dbsp_tpu/obs/flight.py", "FlightRecorder"),
    ("dbsp_tpu/obs/flight.py", "CompiledFlightSource"),
    ("dbsp_tpu/obs/flight.py", "ControllerFlightSource"),
    ("dbsp_tpu/obs/flight.py", "HostFlightSource"),
    ("dbsp_tpu/obs/timeline.py", "Timeline"),
    ("dbsp_tpu/obs/tracing.py", "SpanRecorder"),
    ("dbsp_tpu/obs/tracing.py", "E2ETracer"),
    ("dbsp_tpu/obs/slo.py", "SLOConfig"),
    ("dbsp_tpu/obs/slo.py", "SLOWatchdog"),
    ("dbsp_tpu/obs/registry.py", "MetricsRegistry"),
    ("dbsp_tpu/obs/registry.py", "Metric"),
    ("dbsp_tpu/obs/registry.py", "Counter"),
    ("dbsp_tpu/obs/registry.py", "Gauge"),
    ("dbsp_tpu/obs/registry.py", "Histogram"),
    ("dbsp_tpu/obs/registry.py", "Summary"),
    ("dbsp_tpu/serving.py", "ReadPlane"),
    ("dbsp_tpu/serving.py", "_ViewState"),
    ("dbsp_tpu/serving.py", "ReplicaServer"),
)

#: extra modules swept for C003 (private-lock reach-through) beyond the
#: ones CONCURRENCY_CLASSES already names
REACH_THROUGH_MODULES: Tuple[str, ...] = (
    "dbsp_tpu/obs/instrument.py",
    "dbsp_tpu/io/config.py",
)

# Deliberately NOT schema'd (documented, not forgotten):
#   * obs/registry.py ``_Child``/``_Bound`` — per-label-set value cells,
#     guarded by the owning Metric's ``_lock``; they have no methods of
#     their own and every mutation goes through Metric._inc/_set/_observe
#     under that lock.
#   * the per-request ``Handler`` classes nested in the HTTP servers —
#     one instance per request, no shared state of their own.
#   * the engine layer (CompiledHandle/CircuitHandle/Spines) — serialized
#     by the controller step lock by design; its fields are the
#     *checkpoint* schema's concern, and every serving-path entry point
#     is covered by the controller/server claims here.
#   * serving.py ``_Run``/``ViewSnapshot`` — immutable value objects
#     (``__slots__``, every field bound once in ``__init__``); the
#     lock-free read contract depends on them never mutating, which the
#     ``_ViewState.snap`` claim below pins at the pointer swap.

CONCURRENCY_SCHEMA: Dict[str, Dict[str, str]] = {
    "Controller": {
        "handle": "immutable",
        "catalog": "immutable",
        "config": "immutable",
        "checkpoint_dir": "immutable",
        "checkpoint_every": "immutable",
        "_residency_cfg": "immutable",
        "inputs": "gil-atomic: endpoint wiring is single-threaded deploy "
                  "work before start(); post-start the dicts are only read",
        "outputs": "gil-atomic: endpoint wiring is single-threaded deploy "
                   "work before start(); post-start the dicts are only read",
        "state": "writelock(_lifecycle_lock)",
        "steps": "writelock(_step_lock)",
        "_stop": "immutable",
        "_running": "immutable",
        "_pushed_lock": "immutable",
        "_step_lock": "immutable",
        "_lifecycle_lock": "immutable",
        "_pushed": "lock(_pushed_lock)",
        "total_pushed": "writelock(_pushed_lock)",
        "_thread": "writelock(_lifecycle_lock)",
        "_monitors": "gil-atomic: append-only list appended at deploy "
                     "time; the circuit loop's iteration tolerates a "
                     "mid-append snapshot under the GIL",
        "last_checkpoint_tick": "writelock(_step_lock)",
        "checkpoints": "writelock(_step_lock)",
        "checkpoint_error": "writelock(_step_lock)",
        "_last_ckpt_step": "writelock(_step_lock)",
        "flight": "gil-atomic: wired once by PipelineObs.attach_controller "
                  "before start(); read-only afterwards",
        "timeline": "gil-atomic: wired once by PipelineObs."
                    "attach_controller before start(); read-only "
                    "afterwards (note_* calls go through the timeline's "
                    "own lock)",
        "read_plane": "immutable",
        "e2e": "immutable",
    },
    "_InputEndpoint": {
        "name": "immutable",
        "collection": "immutable",
        "transport": "immutable",
        "parser": "immutable",
        "notify_arrival": "immutable",
        "lock": "immutable",
        "rows": "lock(lock)",
        "skip_rows": "lock(lock)",
        "eoi": "writelock(lock)",
        "error": "writelock(lock)",
        "total_records": "writelock(lock)",
        "total_bytes": "writelock(lock)",
        "paused": "lockset: single writer — the circuit loop's "
                  "backpressure pass; stats() reads tolerate staleness",
    },
    "_OutputEndpoint": {
        "name": "immutable",
        "collection": "immutable",
        "transport": "immutable",
        "encoder": "immutable",
        "cursor": "immutable",
        "total_records": "lockset: mutated only on paths serialized by "
                         "the owning controller's step lock",
        "total_bytes": "lockset: mutated only on paths serialized by "
                       "the owning controller's step lock",
        "error": "lockset: mutated only on paths serialized by the "
                 "owning controller's step lock; stats() reads tolerate "
                 "staleness",
        "pending": "lockset: mutated only on paths serialized by the "
                   "owning controller's step lock (emission, restore, "
                   "checkpoint)",
    },
    "CircuitServer": {
        "controller": "immutable",
        "profiler": "immutable",
        "obs": "immutable",
        "analysis_findings": "immutable",
        "httpd": "immutable",
        "port": "immutable",
        "_thread": "gil-atomic: wired once by start()",
        "_last_profile": "gil-atomic: last-served-report cache — one "
                         "reference assignment per query; /debug's read "
                         "is last-write-wins by design",
        "_last_lineage": "gil-atomic: last-served-report cache — one "
                         "reference assignment per query; /debug's read "
                         "is last-write-wins by design",
    },
    "PipelineManager": {
        "programs": "lock(lock)",
        "pipelines": "lock(lock)",
        "storage_path": "immutable",
        "lock": "immutable",
        "compiler": "immutable",
        "httpd": "immutable",
        "port": "immutable",
        "_thread": "gil-atomic: wired once by start()",
    },
    "Pipeline": {
        "name": "immutable",
        "program": "immutable",
        "config": "immutable",
        "status": "gil-atomic: one deploying writer, then the shutdown "
                  "caller — ordered by the observed status transition; "
                  "reference assignments, describe() reads tolerate "
                  "in-progress values",
        "controller": "gil-atomic: see status",
        "server": "gil-atomic: see status",
        "port": "gil-atomic: see status",
        "error": "gil-atomic: see status",
        "mode": "gil-atomic: see status",
        "obs": "gil-atomic: see status",
        "fallback_reason": "gil-atomic: see status",
        "restored_tick": "gil-atomic: see status",
        "replicas": "gil-atomic: scaled by operator actions (replica "
                    "routes); list append/replace are single GIL-atomic "
                    "ops and fanout_read snapshots the list reference "
                    "before indexing",
        "_fanout_rr": "gil-atomic: racy round-robin counter — concurrent "
                      "increments may collide, costing distribution "
                      "fairness, never correctness",
        "_replica_gauge": "gil-atomic: wired once on the first "
                          "add_replicas; one reference assignment",
        "_replica_breached": "gil-atomic: per-replica breach latch keyed "
                             "by name; writers (scrape collector, "
                             "replicas route) are last-write-wins on a "
                             "boolean by design",
    },
    "_CompilerService": {
        "mgr": "immutable",
        "q": "immutable",
        "thread": "immutable",
    },
    "FileInputTransport": {
        "name": "immutable",
        "replays_from_start": "immutable",
        "path": "immutable",
        "chunk_size": "immutable",
        "follow": "immutable",
        "_paused": "immutable",
        "_stop": "immutable",
        "_thread": "gil-atomic: wired once by start(); join() only reads",
    },
    "FileOutputTransport": {
        "name": "immutable",
        "_lock": "immutable",
        "_f": "lock(_lock)",
    },
    "KafkaInputTransport": {
        "name": "immutable",
        "_kind": "immutable",
        "_mod": "immutable",
        "brokers": "immutable",
        "topics": "immutable",
        "group_id": "immutable",
        "poll_timeout": "immutable",
        "_stop": "immutable",
        "_paused": "immutable",
        "_consumer": "gil-atomic: assigned once by start() before the "
                     "reader thread exists; configure_retry/retries "
                     "reads tolerate None pre-start",
        "_retry_cfg": "gil-atomic: written at endpoint wiring "
                      "(configure_retry) before start()",
        "error": "gil-atomic: single writer (the reader thread), "
                 "monotone None->str; stats() reads tolerate staleness",
    },
    "KafkaOutputTransport": {
        "name": "immutable",
        "_kind": "immutable",
        "_mod": "immutable",
        "topic": "immutable",
        "_producer": "immutable",
    },
    "MiniKafkaBroker": {
        "lock": "immutable",
        "server": "immutable",
        "host": "immutable",
        "port": "immutable",
        "address": "immutable",
        "_thread": "immutable",
        "topics": "lock(lock)",
        "offsets": "lock(lock)",
        "_conns": "lock(lock)",
    },
    "_Conn": {
        "addr": "immutable",
        "lock": "immutable",
        "timeout_s": "writelock(lock)",
        "max_retries": "writelock(lock)",
        "backoff_s": "writelock(lock)",
        "retries": "writelock(lock)",
        "sock": "lock(lock)",
        "rfile": "lock(lock)",
    },
    "MiniConsumer": {
        "topics": "immutable",
        "group": "immutable",
        "conn": "immutable",
    },
    "MiniProducer": {
        "conn": "immutable",
        "lock": "immutable",
        "_pending": "lock(lock)",
    },
    "FlightRecorder": {
        "capacity": "immutable",
        "_lock": "immutable",
        "_ring": "lock(_lock)",
        "_seq": "lock(_lock)",
        "dropped": "writelock(_lock)",
        "dropped_by_source": "lock(_lock)",
    },
    "Timeline": {
        "capacity": "immutable",
        "enabled": "immutable",
        "pipeline": "immutable",
        "_lock": "immutable",
        "_records": "lock(_lock)",
        "_seq": "lock(_lock)",
        "dropped": "writelock(_lock)",
        "_flight_seen": "lock(_lock)",
        "_pending_rows": "lock(_lock)",
        "_oldest_pending_ts": "lock(_lock)",
        "_last_visible_ts": "lock(_lock)",
        "_freshness": "lock(_lock)",
        "_spike_metric_seen": "lock(_lock)",
        "_fresh_hist": "immutable",
        "_stale_gauge": "immutable",
        "_spike_counter": "immutable",
    },
    "SpanRecorder": {
        "process": "immutable",
        "pid": "immutable",
        "_lock": "immutable",
        "_steps": "lock(_lock)",
        "_open": "lock(_lock)",
        "_depth": "lock(_lock)",
        "_threads": "lock(_lock)",
        "dropped_steps": "writelock(_lock)",
        "_dropped_counter": "gil-atomic: wired once by bind() during obs "
                            "attach, before any traffic; read-only "
                            "afterwards",
        "_pipeline": "gil-atomic: wired once by bind() during obs attach, "
                     "before any traffic; read-only afterwards",
    },
    "E2ETracer": {
        "enabled": "gil-atomic: boolean kill-switch latch resolved at "
                   "construction from DBSP_TPU_TRACE_E2E and toggled only "
                   "by A/B harnesses between blocks; a racy read costs at "
                   "most one stray sample",
        "max_pending": "immutable",
        "max_epochs": "immutable",
        "_lock": "immutable",
        "_seq": "lock(_lock)",
        "_pending": "lock(_lock)",
        "_in_tick": "lock(_lock)",
        "_awaiting": "lock(_lock)",
        "_tick_t0": "lock(_lock)",
        "_by_epoch": "lock(_lock)",
        "dropped": "writelock(_lock)",
        "_hist": "gil-atomic: wired once by bind() during obs attach, "
                 "before any traffic; read-only afterwards",
        "_spans": "gil-atomic: wired once by bind() during obs attach, "
                  "before any traffic; read-only afterwards",
        "_timeline": "gil-atomic: wired once by bind() during obs attach, "
                     "before any traffic; read-only afterwards",
    },
    "CompiledFlightSource": {
        "ch": "immutable",
        "flight": "immutable",
        "_lock": "immutable",
        "_lat_seen": "lock(_lock)",
        "_cause_seen": "lock(_lock)",
        "_overhead_seen": "lock(_lock)",
        "_replays_seen": "lock(_lock)",
        "_rows_moved_seen": "lock(_lock)",
        "_consolidate_seen": "lock(_lock)",
        "_residency_seen": "lock(_lock)",
        "_cold_seen": "lock(_lock)",
        "_clock_ns": "lock(_lock)",
    },
    "ControllerFlightSource": {
        "controller": "immutable",
        "flight": "immutable",
        "_lock": "immutable",
        "_errors_seen": "lock(_lock)",
    },
    "HostFlightSource": {
        "circuit": "immutable",
        "flight": "immutable",
        "_spines": "immutable",
        "_spine_nids": "immutable",
        "_exchanges": "immutable",
        "_wm_ops": "immutable",
        "_depth": "lockset: mutated only by scheduler-event callbacks, "
                  "serialized by whatever drives step() — the "
                  "controller's step lock on the serving path",
        "_step_t0": "lockset: see _depth",
        "_tick": "lockset: see _depth",
        "_merged_seen": "lockset: see _depth",
        "_exch_seen": "lockset: see _depth",
        "_wm_lag_seen": "lockset: see _depth",
        "_res_seen": "lockset: see _depth",
    },
    "SLOConfig": {
        "p99_tick_seconds": "immutable",
        "tick_p50_multiple": "immutable",
        "watermark_lag": "immutable",
        "fallback_to_host": "immutable",
        "overflow_replays": "immutable",
        "window_ticks": "immutable",
        "window_s": "immutable",
    },
    "SLOWatchdog": {
        "flight": "immutable",
        "config": "immutable",
        "pipeline": "immutable",
        "freeze_window": "immutable",
        "_lock": "immutable",
        "_breach_counter": "immutable",
        "_incidents_counter": "immutable",
        "_seen_seq": "lock(_lock)",
        "_ticks": "lock(_lock)",
        "_replay_ts": "lock(_lock)",
        "_wm_lag": "lock(_lock)",
        "_fallback": "lock(_lock)",
        "_transport": "lock(_lock)",
        "_restore_failed": "lock(_lock)",
        "_restores": "lock(_lock)",
        "_active": "lock(_lock)",
        "_incidents": "lock(_lock)",
        "_ids": "lock(_lock)",
    },
    "MetricsRegistry": {
        "_lock": "immutable",
        "_metrics": "lock(_lock)",
        "_collectors": "lock(_lock)",
    },
    "Metric": {
        "kind": "immutable",
        "name": "immutable",
        "help": "immutable",
        "label_names": "immutable",
        "_lock": "immutable",
        "_children": "lock(_lock)",
    },
    "ReadPlane": {
        "enabled": "immutable",
        "capacity": "immutable",
        "compact_after": "immutable",
        "_lock": "immutable",
        "_wakeup": "immutable",
        "_views": "writelock(_lock): registered at controller "
                  "construction (add_view); reader routes do one "
                  "GIL-atomic dict lookup",
        "epoch": "writelock(_lock): monotone int; changefeed/stats reads "
                 "are single loads",
        "publishes": "writelock(_lock)",
        "last_publish_ts": "writelock(_lock)",
        "flight": "gil-atomic: wired once by bind() before traffic; one "
                  "reference assignment",
        "_read_qps": "gil-atomic: wired once by bind() before traffic; "
                     "the idempotence guard's read tolerates None",
        "_read_seconds": "gil-atomic: see _read_qps",
        "_publish_total": "gil-atomic: see _read_qps",
    },
    "_ViewState": {
        "name": "immutable",
        "handle": "immutable",
        "mode": "immutable",
        "nkeys": "lockset: written only under the owning plane's _lock "
                 "(publish/restore); monotone None->int, lock-free reads "
                 "are single loads",
        "cid": "lockset: rebound only under the owning plane's _lock "
               "(restore re-registration)",
        "snap": "lockset: the lock-free read contract — publication "
                "swaps this pointer under the owning plane's _lock; "
                "readers resolve it with ONE GIL-atomic load and then "
                "touch only the immutable ViewSnapshot",
        "prev_rows": "lockset: publisher-only diff base, mutated under "
                     "the owning plane's _lock",
        "feed": "lockset: appended/cleared under the owning plane's "
                "_lock; changefeed reads snapshot it via list(feed) — "
                "atomic under the GIL on a deque",
        "dropped_epoch": "lockset: written under the owning plane's "
                         "_lock; monotone int, lock-free reads are "
                         "single loads",
        "seen_step": "lockset: publisher-only cursor, mutated under the "
                     "owning plane's _lock",
    },
    "ReplicaServer": {
        "primary": "immutable",
        "views_served": "immutable",
        "name": "immutable",
        "poll_timeout_s": "immutable",
        "_lock": "immutable",
        "_state": "writelock(_lock)",
        "_cursor": "writelock(_lock)",
        "_nkeys": "writelock(_lock)",
        "_applied_ts": "writelock(_lock)",
        "_sorted": "writelock(_lock): per-view cache cell — readers do "
                   "one GIL-atomic load and rebuild under the lock on "
                   "miss; a racy extra rebuild is benign",
        "applied": "writelock(_lock)",
        "stalled": "gil-atomic: boolean latch toggled by the "
                   "stall()/resume() caller; the feed loop's read is a "
                   "benign race (one extra poll)",
        "_stop": "immutable",
        "_httpd": "immutable",
        "port": "immutable",
        "_serve_thread": "immutable",
        "_feed_thread": "immutable",
        "e2e": "immutable",
        "spans": "immutable",
        "_trace": "writelock(_lock)",
    },
    "Counter": {},
    "Gauge": {},
    "Histogram": {
        "bounds": "immutable",
    },
    "Summary": {
        "quantiles": "immutable",
    },
}


class Guard(NamedTuple):
    kind: str                 # immutable|lock|writelock|owner|lockset|
    lock: Optional[str]       # gil-atomic; attr name for lock/writelock
    note: Optional[str]


_GUARD_RE = re.compile(
    r"^(immutable|owner|lockset|gil-atomic"
    r"|(?:lock|writelock)\(([A-Za-z_][A-Za-z0-9_]*)\))"
    r"(?::\s*(.+))?$", re.S)


class GuardError(ValueError):
    pass


def parse_guard(value: str) -> Guard:
    """Parse one schema guard string; raises :class:`GuardError` on a
    malformed guard or a ``gil-atomic`` without its rationale."""
    m = _GUARD_RE.match(value.strip())
    if m is None:
        raise GuardError(
            f"malformed guard {value!r} (expected immutable | lock(X) | "
            "writelock(X) | owner | lockset | gil-atomic: <why>)")
    head, lock, note = m.group(1), m.group(2), m.group(3)
    kind = head.split("(")[0]
    if kind == "gil-atomic" and not (note and note.strip()):
        raise GuardError(
            "gil-atomic claims must state their invariant: "
            f"'gil-atomic: <why>' (got {value!r})")
    return Guard(kind, lock, note.strip() if note else None)


def effective_schema(class_name: str,
                     bases: Dict[str, Tuple[str, ...]],
                     schema_map: Optional[Dict[str, Dict[str, str]]] = None,
                     ) -> Dict[str, str]:
    """The merged guard dict for ``class_name``: its own entry layered
    over its (transitive) base classes' entries. ``bases`` maps class
    name -> direct base names (the static pass derives it from the AST;
    the runtime derives it from the MRO). ``schema_map`` defaults to
    :data:`CONCURRENCY_SCHEMA` (tests layer gallery classes over it)."""
    schema_map = CONCURRENCY_SCHEMA if schema_map is None else schema_map
    out: Dict[str, str] = {}

    def fold(name: str) -> None:
        for b in bases.get(name, ()):
            fold(b)
        out.update(schema_map.get(name, {}))

    fold(class_name)
    return out
